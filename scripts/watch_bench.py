#!/usr/bin/env python
"""Watch/TTL fanout benchmark (PR 9, ROADMAP item 5): sustained
watch-event deliveries/s with 100k+ live watchers and 10k+ TTL
expiries/s, plus the slow-watcher overflow probe (counted eviction vs
opt-in backpressure).

The scale leg registers W watchers in batched form (one hub lock for
the lot): mostly exact stream watchers over the churn key space, a
handful of recursive watchers on the churn root (the mass-discovery
shape: every client watches its own keys, a few aggregators watch
everything), and a tracked cohort with dedicated drainers that
asserts ZERO events lost within the history window.  A writer thread
creates short-TTL keys and a sweeper thread runs the bulk
``delete_expired_keys`` sweep at the SYNC cadence — every expiry is a
watch event, so deliveries/s >= 2x expiries/s (create + expire per
exact watcher) plus the recursive fan-out.

Run:
    python scripts/watch_bench.py              # full scale leg
    python scripts/watch_bench.py --check      # + gate the targets
    python scripts/watch_bench.py --smoke      # tier-1 wiring (fast)

``--check`` gates: watchers >= --watchers (default 100k), expiries/s
>= --expiry-rate (default 10k), zero tracked-watcher loss, overflow
probe evicts (and the backpressure arm delivers all with zero
evictions).  Full runs write
``bench_artifacts/watch_fanout_<stamp>.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from etcd_tpu.obs.metrics import registry  # noqa: E402
from etcd_tpu.store import PERMANENT, Store  # noqa: E402

_ART_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench_artifacts")


def _delivered() -> float:
    return registry.counter("etcd_watch_delivered_total").get()


def _evictions() -> float:
    return (registry.counter("etcd_watch_evictions_total",
                             reason="overflow").get()
            + registry.counter("etcd_watch_evictions_total",
                               reason="stall").get())


def _snap(h) -> dict:
    s = h.snapshot()
    return {k: s[k] for k in ("count", "sum", "p50", "p99", "max")}


def scale_leg(watchers: int, duration: float, expiry_rate: int,
              recursive_watchers: int = 4,
              tracked: int = 64) -> dict:
    """The headline row: W live watchers, TTL churn at the target
    expiry rate, deliveries measured process-wide."""
    s = Store(history_capacity=4096)
    s.fanout.start(workers=int(os.environ.get("ETCD_WATCH_WORKERS",
                                              "1")))
    keyspace = max(1024, watchers - recursive_watchers - tracked)

    # -- batched registration (one hub lock round trip) ------------
    t0 = time.perf_counter()
    specs = [(f"/svc/k{i}", False, True, 0) for i in range(keyspace)]
    specs += [("/svc", True, True, 0)
              for _ in range(recursive_watchers)]
    specs += [(f"/svc/k{i}", False, True, 0) for i in range(tracked)]
    ws = s.watch_many(specs)
    reg_s = time.perf_counter() - t0
    live = s.watcher_hub.count
    rec_ws = ws[keyspace:keyspace + recursive_watchers]
    tracked_ws = ws[keyspace + recursive_watchers:]
    for w in rec_ws + tracked_ws:
        # the drained cohorts are aggregators: a whole bulk-expiry
        # batch lands in their queue in one delivery pass, so they
        # need depth beyond the 100-slot client default to absorb
        # the burst between scheduler slices
        w.event_queue.maxsize = 65536

    # -- consumers --------------------------------------------------
    # recursive watchers see EVERY event: drain them hard so they
    # are the fast cohort, not the evicted one
    stop_load = threading.Event()
    stop = threading.Event()
    rec_counts = [0] * recursive_watchers
    tracked_counts = [0] * tracked

    def drain(w, counts, i):
        while True:
            e = w.next_event(timeout=0.2)
            if e is None:
                if stop.is_set() or w.removed:
                    return
                continue
            counts[i] += 1

    drains = []
    for i, w in enumerate(rec_ws):
        t = threading.Thread(target=drain, args=(w, rec_counts, i),
                             daemon=True)
        t.start()
        drains.append(t)
    for i, w in enumerate(tracked_ws):
        t = threading.Thread(target=drain, args=(w, tracked_counts, i),
                             daemon=True)
        t.start()
        drains.append(t)

    # -- load -------------------------------------------------------
    # writer creates short-TTL keys round-robin; sweeper expires them
    # in bulk at the SYNC cadence.  The writer paces itself to the
    # target create rate == expiry rate (steady state).
    created = [0]
    tracked_created = [0]
    ttl = 0.05
    sweep_every = 0.1

    def writer():
        i = 0
        t_start = time.perf_counter()
        while not stop_load.is_set():
            now = time.time()
            # tracked keys churn with the herd (tracked cohort is a
            # slice of the exact key space)
            s.create(f"/svc/k{i % keyspace}", False, "v", False,
                     now + ttl)
            created[0] += 1
            if i % keyspace < tracked:
                tracked_created[0] += 1
            i += 1
            # pace to the target rate
            ahead = created[0] / expiry_rate \
                - (time.perf_counter() - t_start)
            if ahead > 0.002:
                time.sleep(min(ahead, 0.01))

    def sweeper():
        while not stop_load.is_set():
            s.delete_expired_keys(time.time())
            time.sleep(sweep_every)

    d0 = _delivered()
    e0 = s.stats.expire_count
    ev0 = _evictions()

    wt = threading.Thread(target=writer, daemon=True)
    st_t = threading.Thread(target=sweeper, daemon=True)
    t0 = time.perf_counter()
    wt.start()
    st_t.start()
    time.sleep(duration)
    stop_load.set()
    wt.join(timeout=5)
    st_t.join(timeout=5)
    # final sweep + engine settle BEFORE the drainers are released so
    # the tracked accounting closes over every emitted event
    s.delete_expired_keys(time.time() + ttl + 1)
    s.fanout.drain(timeout=5)
    wall = time.perf_counter() - t0
    stop.set()
    for t in drains:
        t.join(timeout=5)

    expiries = s.stats.expire_count - e0
    delivered = _delivered() - d0
    evictions = _evictions() - ev0

    # zero-loss check: per churn a tracked exact watcher sees the
    # create (1) plus the expire twice (removed-path callback AND
    # original-path fan-out — reference notifyWatchers parity), so
    # exactly 3 events per tracked create; the cohort was drained
    # continuously, so the history window never mattered
    expected_tracked = 3 * tracked_created[0]
    got_tracked = sum(tracked_counts)
    lost = max(0, expected_tracked - got_tracked)
    return {
        "watchers_live": live,
        "register_s": round(reg_s, 4),
        "register_per_s": round(live / reg_s),
        "duration_s": round(wall, 2),
        "creates": created[0],
        "expiries": expiries,
        "expiries_per_s": round(expiries / wall),
        "delivered": delivered,
        "delivered_per_s": round(delivered / wall),
        "recursive_watchers": recursive_watchers,
        "recursive_events_per_s": round(sum(rec_counts) / wall),
        "tracked_watchers": tracked,
        "tracked_expected": expected_tracked,
        "tracked_got": got_tracked,
        "tracked_lost": lost,
        "evictions": evictions,
        "ttl_batch": _snap(registry.histogram(
            "etcd_ttl_expire_batch_size")),
        "dispatch_match": _snap(registry.histogram(
            "etcd_watch_dispatch_seconds", stage="match")),
        "dispatch_deliver": _snap(registry.histogram(
            "etcd_watch_dispatch_seconds", stage="deliver")),
    }


def overflow_probe(policy: str, events: int = 400,
                   drain_every: float | None = None) -> dict:
    """Slow-watcher policy probe: one watcher, a writer far faster
    than its consumer.  ``evict``: the watcher must be evicted and
    counted.  ``block``: with a (slow) consumer the producer is
    backpressured and EVERY event arrives, zero evictions."""
    s = Store()
    s.fanout.overflow = policy
    s.fanout.block_s = 5.0 if policy == "block" else None
    w = s.watch("/of", False, True, 0)
    w.event_queue.maxsize = 32
    ev0 = _evictions()
    got = [0]
    stop = threading.Event()

    def consumer():
        while not stop.is_set():
            e = w.next_event(timeout=0.2)
            if e is None:
                if w.removed and policy == "evict":
                    return
                continue
            got[0] += 1
            if drain_every:
                time.sleep(drain_every)

    ct = threading.Thread(target=consumer, daemon=True)
    ct.start()
    t0 = time.perf_counter()
    for i in range(events):
        s.set("/of", False, str(i), PERMANENT)
    wall = time.perf_counter() - t0
    # let the consumer finish
    deadline = time.monotonic() + 10
    while policy == "block" and got[0] < events \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    stop.set()
    ct.join(timeout=5)
    evictions = _evictions() - ev0
    return {
        "policy": policy,
        "events": events,
        "consumed": got[0],
        "evicted": bool(w.removed),
        "evictions_counted": evictions,
        "producer_wall_s": round(wall, 4),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--watchers", type=int, default=100_000)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--expiry-rate", type=int, default=12_000,
                    help="target creates/s == expiries/s")
    ap.add_argument("--check", action="store_true",
                    help="gate the scale + policy targets")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fast run for scripts/test (gates "
                    "behavior, not scale)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.watchers = 2_000
        args.duration = 1.5
        args.expiry_rate = 2_000

    out = {"metric": "watch_fanout",
           "watchers": args.watchers,
           "expiry_rate_target": args.expiry_rate}
    row = scale_leg(args.watchers, args.duration, args.expiry_rate)
    out["scale"] = row
    # overflow behavior, both arms: eviction is the counted default,
    # backpressure the opt-in — measured on every run so the artifact
    # always carries the policy evidence
    out["overflow_evict"] = overflow_probe("evict",
                                           drain_every=0.001)
    out["overflow_block"] = overflow_probe("block",
                                           drain_every=0.001)
    print(json.dumps(out, indent=2))

    failures = []
    # behavior gates (smoke and check)
    if row["tracked_lost"]:
        failures.append(
            f"tracked watchers lost {row['tracked_lost']} events")
    if not out["overflow_evict"]["evicted"] \
            or out["overflow_evict"]["evictions_counted"] < 1:
        failures.append("evict policy: no counted eviction")
    if out["overflow_block"]["evictions_counted"] \
            or out["overflow_block"]["consumed"] \
            != out["overflow_block"]["events"]:
        failures.append("block policy: lost events or evicted")
    if args.check:
        if row["watchers_live"] < args.watchers:
            failures.append(
                f"watchers_live {row['watchers_live']} "
                f"< {args.watchers}")
        if row["expiries_per_s"] < args.expiry_rate * 0.8:
            failures.append(
                f"expiries/s {row['expiries_per_s']} < 0.8x target "
                f"{args.expiry_rate}")
    if args.smoke:
        # smoke keeps behavior honest at small scale
        if row["watchers_live"] < args.watchers:
            failures.append("smoke: registration incomplete")
        if row["expiries"] <= 0 or row["delivered"] <= 0:
            failures.append("smoke: no expiries/deliveries measured")

    if not args.smoke:
        os.makedirs(_ART_DIR, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        path = os.path.join(_ART_DIR, f"watch_fanout_{stamp}.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {path}", file=sys.stderr)

    if failures:
        print("WATCH BENCH GATE FAILED:", "; ".join(failures),
              file=sys.stderr)
        return 1
    print("watch_bench ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
