"""Race the raw-CRC contraction variants on the current backend.

For each variant (production XLA path, Pallas kernel, and the
ops/crc_variants.py candidates) this measures the device-sustained
rate with the same methodology as bench.py's primary metric: the
batch stays device-resident, the body XORs the loop index in so XLA
cannot hoist it, and one scalar fetch at the end is the only sync.
A correctness gate (iteration-0 chain verify against stored CRCs)
must pass or the variant's number is reported as failed.

Prints one JSON line per variant plus a `best` summary line.

  python scripts/crc_variants_bench.py [N_ROWS] [WIDTH] [ITERS]

(Run under the tunnel for real-chip numbers; runs anywhere for a
relative CPU sanity check, labeled by backend.)
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 18
    width = int(sys.argv[2]) if len(sys.argv) > 2 else 384
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 8

    import jax

    # env-only platform selection loses to the axon plugin's
    # import-time override (tests/conftest.py pattern); honor an
    # explicit JAX_PLATFORMS at the config level so CPU runs never
    # hang on a dead relay
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from etcd_tpu.crc import crc32c
    from etcd_tpu.obs import roofline
    from etcd_tpu.ops.crc_device import (
        _raw_crc_jit,
        chain_links_injected,
        contribution_matrix,
        inject_seeds,
    )
    from etcd_tpu.ops.crc_variants import VARIANTS, plane_matrices

    backend = jax.default_backend()

    # Measured MFU denominator for the per-variant roofline fields
    # (obs/roofline.py is the single source of truth for every
    # MFU/entries-per-TFLOP derivation — PR 2).  The probe costs a
    # ~1.1 TFLOP train: free on a chip, minutes on the 1-core CPU
    # box, so CPU runs skip it unless explicitly asked.
    ceiling_bf16 = None
    if backend == "tpu" or os.environ.get("BENCH_PROBE_CEILING"):
        ceiling_bf16 = roofline.probe_matmul_ceiling(jax, "bf16")
        print(json.dumps({"env_matmul_tflops_bf16":
                          round(ceiling_bf16, 2)
                          if ceiling_bf16 else None}), flush=True)

    # synthetic right-aligned chained records (seed-injected, so every
    # variant's gate is the full rolling-chain verify).  Generation is
    # vectorized — a python-loop crc32c.update over N rows costs tens
    # of minutes of a live tunnel session at N=1M: raw CRCs come from
    # one batched contraction, the rolling chain from a GF(2) matvec
    # scan (~23 us/row), and an INDEPENDENT host-table CRC spot check
    # over 256 random rows guards against the generator and the
    # device-under-test sharing a bug.
    from etcd_tpu.crc import gf2

    c = jnp.asarray(contribution_matrix(width))
    t_gen = time.perf_counter()
    rng = np.random.default_rng(3)
    lens = rng.integers(width // 2, width - 4, size=n)
    fill = rng.integers(0, 256, size=(n, width), dtype=np.uint8)
    mask = np.arange(width)[None, :] >= (width - lens)[:, None]
    rows = np.where(mask, fill, 0).astype(np.uint8)
    del fill, mask
    raw = np.asarray(_raw_crc_jit(rows, c, use_pallas=False))
    zmats = {int(ln): gf2.zero_operator(int(ln))
             for ln in np.unique(lens)}
    stored = np.empty(n, np.uint32)
    prev_ = np.empty(n, np.uint32)
    chain = 0
    inv = 0xFFFFFFFF
    for i in range(n):
        prev_[i] = chain
        chain = (gf2.matvec(zmats[int(lens[i])], chain ^ inv)
                 ^ int(raw[i]) ^ inv)
        stored[i] = chain
    # independent gate on the generator itself: host table CRC
    for i in rng.choice(n, size=min(n, 256), replace=False):
        li = int(lens[i])
        want = crc32c.update(int(prev_[i]),
                             rows[i, width - li:].tobytes())
        assert want == int(stored[i]), f"generator mismatch at {i}"
    inject_seeds(rows, lens, prev_)
    print(json.dumps({"generated": n,
                      "seconds": round(time.perf_counter() - t_gen,
                                       1)}), flush=True)

    drows = jax.device_put(rows)
    dstored = jax.device_put(stored)

    ck = jnp.asarray(plane_matrices(width))

    def make_fn(name):
        """(raw_fn, perturb_fn) for one variant: ``raw_fn(buf)``
        computes raw CRCs, ``perturb_fn(buf, i)`` (pallas_planes
        kernels only) folds the LICM-defeating XOR into the kernel
        via the SMEM scalar.  The race loop below uses perturb_fn
        when present — the SAME measured form bench.py's sustained
        loop runs — so promotion ranks kernels under the bench's
        overhead, not under an extra outer HBM pass the bench never
        pays (ADVICE r5)."""
        if name == "xla":
            return (lambda b: _raw_crc_jit(b, c,
                                           use_pallas=False)), None
        if name == "pallas":
            return (lambda b: _raw_crc_jit(b, c,
                                           use_pallas=True)), None
        from etcd_tpu.ops import crc_variants

        # same name grammar as BENCH_CRC_VARIANT (one validator: a
        # name the race promotes must be one the bench accepts)
        base, tile = crc_variants.parse_variant(name)
        if base.startswith("pallas_planes"):
            # same default-tile resolution as the bench wrappers
            # (ETCD_CRC_TILE override included) — the promoted name
            # must denote the same measured kernel in both
            t = tile or crc_variants._planes_env_tile()
            transposed = base.endswith("_t")
            interp = backend != "tpu"
            return (lambda b: crc_variants._pallas_planes_jit(
                b, ck, t, transposed, interp),
                lambda b, i: crc_variants._pallas_planes_jit(
                    b, ck, t, transposed, interp, perturb=i))
        jit_map = {"planes": lambda b: crc_variants._planes_jit(b, ck),
                   "transposed":
                   lambda b: crc_variants._transposed_jit(b, c),
                   "planes_t":
                   lambda b: crc_variants._planes_t_jit(b, ck),
                   "int4": lambda b: crc_variants._int4_jit(b, c),
                   "planes4":
                   lambda b: crc_variants._planes4_jit(b, ck)}
        return jit_map[base], None

    from etcd_tpu.ops import crc_variants as _cv

    # every registered variant races (future VARIANTS additions are
    # picked up automatically); on TPU the pallas_planes pair is
    # covered by its explicit tile sweep instead of the default tile
    names = ["xla"] + sorted(VARIANTS)
    if backend == "tpu":
        names.insert(1, "pallas")
        # likely winners (the pallas tile sweep) race BEFORE the
        # speculative int4 bets: an s4 lowering with a pathological
        # compile time must not eat the window's race budget first
        names = [x for x in names
                 if x not in ("pallas_planes", "pallas_planes_t")]
        names += ["pallas_planes@512", "pallas_planes@1024",
                  "pallas_planes@2048",
                  "pallas_planes_t@1024", "pallas_planes_t@2048"]
        names += sorted(_cv.TPU_RACE_VARIANTS)

    results = {}
    for name in names:
        fn, perturb_fn = make_fn(name)

        @functools.partial(jax.jit, static_argnames=("k",))
        def loop(rows_, stored_, k, _fn=fn, _pfn=perturb_fn):
            def body(i, acc):
                if _pfn is not None:
                    # in-kernel SMEM perturbation — bench.py's
                    # sustained-loop form for these kernels; i == 0
                    # stays the unperturbed, correctness-gated pass
                    raw = _pfn(rows_, i)
                else:
                    raw = _fn(rows_ ^ i.astype(jnp.uint8))
                ok = chain_links_injected(raw, stored_)
                return acc + jnp.where(
                    i == 0, jnp.sum(ok, dtype=jnp.int32), 0)

            return jax.lax.fori_loop(0, k, body, jnp.int32(0))

        try:
            t0 = time.perf_counter()
            n_ok = int(loop(drows, dstored, iters))  # compile+gate
            compile_s = time.perf_counter() - t0
            if n_ok != n:
                results[name] = {"error": f"gate {n_ok}/{n}"}
                print(json.dumps({"variant": name,
                                  **results[name]}), flush=True)
                continue
            t0 = time.perf_counter()
            int(loop(drows, dstored, iters))
            dt = time.perf_counter() - t0
            eps = n * iters / dt
            gbps = n * width * iters / dt / 1e9
            results[name] = {"entries_per_sec": round(eps, 1),
                             "gbps": round(gbps, 3),
                             "compile_s": round(compile_s, 2)}
            # roofline-derived fields (generous + honest FLOP
            # definitions; ceiling_suspect tagging on impossible
            # fractions) — same derivation path as bench.py's
            results[name].update(roofline.mfu_fields(
                eps, width,
                measured_tflops_bf16=ceiling_bf16,
                provenance={"probe": "roofline.probe_matmul_ceiling",
                            "bf16_tflops": ceiling_bf16,
                            "backend": backend}))
            print(json.dumps({"variant": name, "backend": backend,
                              **results[name]}), flush=True)
        except Exception as e:  # per-variant isolation
            results[name] = {"error": repr(e)[:200]}
            print(json.dumps({"variant": name,
                              **results[name]}), flush=True)

    ok = {k: v for k, v in results.items() if "entries_per_sec" in v}
    if ok:
        best = max(ok, key=lambda k: ok[k]["entries_per_sec"])
        print(json.dumps({
            "best": best, "backend": backend, "n": n, "width": width,
            "iters": iters, **ok[best]}), flush=True)


if __name__ == "__main__":
    main()
