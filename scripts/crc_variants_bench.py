"""Race the raw-CRC contraction variants on the current backend.

For each variant (production XLA path, Pallas kernel, and the
ops/crc_variants.py candidates) this measures the device-sustained
rate with the same methodology as bench.py's primary metric: the
batch stays device-resident, the body XORs the loop index in so XLA
cannot hoist it, and one scalar fetch at the end is the only sync.
A correctness gate (iteration-0 chain verify against stored CRCs)
must pass or the variant's number is reported as failed.

Prints one JSON line per variant plus a `best` summary line.

  python scripts/crc_variants_bench.py [N_ROWS] [WIDTH] [ITERS]

(Run under the tunnel for real-chip numbers; runs anywhere for a
relative CPU sanity check, labeled by backend.)
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 18
    width = int(sys.argv[2]) if len(sys.argv) > 2 else 384
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 8

    import jax

    # env-only platform selection loses to the axon plugin's
    # import-time override (tests/conftest.py pattern); honor an
    # explicit JAX_PLATFORMS at the config level so CPU runs never
    # hang on a dead relay
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from etcd_tpu.crc import crc32c
    from etcd_tpu.ops.crc_device import (
        _raw_crc_jit,
        chain_links_injected,
        contribution_matrix,
        inject_seeds,
    )
    from etcd_tpu.ops.crc_variants import VARIANTS, plane_matrices

    backend = jax.default_backend()

    # synthetic right-aligned chained records (seed-injected, so every
    # variant's gate is the full rolling-chain verify)
    rng = np.random.default_rng(3)
    lens = rng.integers(width // 2, width - 4, size=n)
    rows = np.zeros((n, width), np.uint8)
    stored = np.empty(n, np.uint32)
    prev_ = np.empty(n, np.uint32)
    chain = 0
    # vectorized-ish generation: fill then fix chains in one pass
    fill = rng.integers(0, 256, size=(n, width), dtype=np.uint8)
    for i in range(n):
        li = int(lens[i])
        rows[i, width - li:] = fill[i, :li]
        prev_[i] = chain
        chain = crc32c.update(chain, rows[i, width - li:].tobytes())
        stored[i] = chain
    inject_seeds(rows, lens, prev_)

    drows = jax.device_put(rows)
    dstored = jax.device_put(stored)

    c = jnp.asarray(contribution_matrix(width))
    ck = jnp.asarray(plane_matrices(width))

    def make_fn(name):
        if name == "xla":
            return lambda b: _raw_crc_jit(b, c, use_pallas=False)
        if name == "pallas":
            return lambda b: _raw_crc_jit(b, c, use_pallas=True)
        from etcd_tpu.ops import crc_variants

        jit_map = {"planes": lambda b: crc_variants._planes_jit(b, ck),
                   "transposed":
                   lambda b: crc_variants._transposed_jit(b, c),
                   "planes_t":
                   lambda b: crc_variants._planes_t_jit(b, ck)}
        return jit_map[name]

    names = ["xla"] + sorted(VARIANTS)
    if backend == "tpu":
        names.insert(1, "pallas")

    results = {}
    for name in names:
        fn = make_fn(name)

        @functools.partial(jax.jit, static_argnames=("k",))
        def loop(rows_, stored_, k, _fn=fn):
            def body(i, acc):
                buf = rows_ ^ i.astype(jnp.uint8)
                ok = chain_links_injected(_fn(buf), stored_)
                return acc + jnp.where(
                    i == 0, jnp.sum(ok, dtype=jnp.int32), 0)

            return jax.lax.fori_loop(0, k, body, jnp.int32(0))

        try:
            t0 = time.perf_counter()
            n_ok = int(loop(drows, dstored, iters))  # compile+gate
            compile_s = time.perf_counter() - t0
            if n_ok != n:
                results[name] = {"error": f"gate {n_ok}/{n}"}
                print(json.dumps({"variant": name,
                                  **results[name]}), flush=True)
                continue
            t0 = time.perf_counter()
            int(loop(drows, dstored, iters))
            dt = time.perf_counter() - t0
            eps = n * iters / dt
            gbps = n * width * iters / dt / 1e9
            results[name] = {"entries_per_sec": round(eps, 1),
                             "gbps": round(gbps, 3),
                             "compile_s": round(compile_s, 2)}
            print(json.dumps({"variant": name, "backend": backend,
                              **results[name]}), flush=True)
        except Exception as e:  # per-variant isolation
            results[name] = {"error": repr(e)[:200]}
            print(json.dumps({"variant": name,
                              **results[name]}), flush=True)

    ok = {k: v for k, v in results.items() if "entries_per_sec" in v}
    if ok:
        best = max(ok, key=lambda k: ok[k]["entries_per_sec"])
        print(json.dumps({
            "best": best, "backend": backend, "n": n, "width": width,
            "iters": iters, **ok[best]}), flush=True)


if __name__ == "__main__":
    main()
