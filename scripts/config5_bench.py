"""Config 5 (BASELINE configs[4]): G raft groups sharded over a
device mesh — batched leader append + msgAppResp absorb + quorum
commit with the match-index quorum running under the mesh's
collectives (parallel/mesh.py make_sharded_step).

Real v5e-8 hardware is not reachable from this harness (one tunneled
chip), so this measures the SAME sharded program on the virtual
N-device CPU mesh the test suite uses and labels the result
accordingly — a measured number for the sharded step's wall time, not
a TPU throughput claim.

Prints ONE JSON line; run via bench.py or standalone:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/config5_bench.py [GROUPS] [ITERS]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    groups = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    from __graft_entry__ import _example_args
    from etcd_tpu.parallel import (
        group_mesh,
        make_sharded_step,
        place_step_inputs,
    )

    mesh = group_mesh(len(jax.devices()))
    ng, ns = mesh.shape["g"], mesh.shape["s"]
    g = max(1, groups // ng) * ng
    args = place_step_inputs(mesh, _example_args(
        n=8 * ng, max_len=8 * ns, g=g, m=5, cap=32))

    step = make_sharded_step(mesh)

    def once():
        out = step(*args)
        jax.block_until_ready(out)
        return out

    t0 = time.perf_counter()
    out = once()  # compile
    compile_s = time.perf_counter() - t0
    assert bool(np.all(np.asarray(out[3]) == 2)), "commit stalled"

    t0 = time.perf_counter()
    for _ in range(iters):
        once()
    dt = (time.perf_counter() - t0) / iters

    # The serving-path form: MultiRaft state sharded over the mesh
    # (multiraft.py shard — what --cohosted-mesh-devices deploys),
    # fused proposal trains running SPMD across the mesh devices.
    from etcd_tpu.raft.multiraft import MultiRaft

    # same log-window/append-window class as the step above (cap 32);
    # e=4 covers the 1-proposal/round serving load with headroom
    mr = MultiRaft(g=g, m=5, cap=32, max_batch_ents=4)
    mr.shard(mesh)
    mr.campaign(0)
    one = np.ones(g, np.int32)
    train = 4
    mr.propose_rounds(one, train)  # compile at this static train
    mr.mark_applied(mr.commit_index())
    mr.compact()
    # average over several fused-train dispatches (same discipline
    # as the step metric above; compaction between trains stays
    # outside the timed regions)
    times = []
    for _ in range(max(2, iters // 2)):
        t0 = time.perf_counter()
        newly = mr.propose_rounds(one, train)
        times.append(time.perf_counter() - t0)
        assert int(newly.sum()) == g * train
        mr.mark_applied(mr.commit_index())
        mr.compact()
    serve_dt = sum(times) / len(times) / train

    print(json.dumps({
        "groups": g, "members": 5,
        "mesh": f"{ng}x{ns} ({len(jax.devices())} virtual cpu "
                f"devices)",
        "backend": "virtual-cpu-mesh",
        "step_ms": round(dt * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "group_commits_per_sec": round(2 * g / dt, 0),
        "serving_sharded_round_ms": round(serve_dt * 1e3, 2),
        "serving_sharded_commits_per_sec": round(g / serve_dt, 0),
    }), flush=True)


if __name__ == "__main__":
    main()
