"""Streaming-replay chunk-size micro-harness (PR 3 satellite).

Sweeps the streaming pipeline's chunk size over the host path —
1 / 4 / 16 / 64 MiB — on a synthetic WAL stream, plus the unchunked
fused pass as the reference point, and writes one JSON artifact to
``bench_artifacts/replay_pipeline_<stamp>.json``.  This is the
measurement behind ``wal/backend_policy.DEFAULT_CHUNK_BYTES``.

    python scripts/replay_bench.py [entries] [payload]
    python scripts/replay_bench.py --smoke

``--smoke`` is the tier-1 wiring (scripts/test): a small blob driven
through BOTH the fused native entry point and the streaming path
end-to-end, with the outputs cross-checked record for record — a fast
structural exercise, not a measurement (no artifact written).

Prints ONE JSON line either way.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402

SWEEP_MIB = (1, 4, 16, 64)
_ART_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench_artifacts")


def _gen(entries: int, payload: int):
    from etcd_tpu import native

    if not native.available():
        print(json.dumps({"error": "native toolchain unavailable"}))
        raise SystemExit(1)
    return native.wal_gen(entries, payload, start_index=1, seed=0)


def sweep(entries: int, payload: int) -> dict:
    from etcd_tpu import native
    from etcd_tpu.wal.replay_device import stream_scan_verify

    blob = _gen(entries, payload)
    out = {"metric": "replay_pipeline_chunk_sweep",
           "entries": entries, "payload": payload,
           "blob_mb": round(blob.nbytes / 1e6, 1), "rows": []}

    def timed(fn):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t = timed(lambda: native.scan_verify(blob, seed=0))
    out["rows"].append({"chunk_mib": None, "mode": "fused-unchunked",
                        "seconds": round(t, 4),
                        "entries_per_sec": round(entries / t, 0)})
    for mib in SWEEP_MIB:
        t = timed(lambda: stream_scan_verify(
            blob, seed=0, route="host", chunk_bytes=mib << 20))
        out["rows"].append({"chunk_mib": mib, "mode": "host-chunked",
                            "seconds": round(t, 4),
                            "entries_per_sec":
                            round(entries / t, 0)})
    return out


def smoke() -> dict:
    """Small blob through the fused entry point AND the streaming
    path (host + fake-device-free stream on the in-process backend),
    outputs cross-checked — exits nonzero on any divergence."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from etcd_tpu import native
    from etcd_tpu.wal.replay_device import stream_scan_verify

    entries, payload = 4096, 64
    blob = _gen(entries, payload)
    fused = native.scan_verify(blob, seed=0)
    two_pass = native.wal_scan(blob)
    chunked = stream_scan_verify(blob, seed=0, route="host",
                                 chunk_bytes=64 << 10)
    streamed = stream_scan_verify(blob, seed=0, route="stream",
                                  chunk_bytes=64 << 10)
    for name, got in (("two-pass", two_pass), ("chunked", chunked),
                      ("streamed", streamed)):
        for i, (a, b) in enumerate(zip(fused, got)):
            if not np.array_equal(a, b):
                print(json.dumps({"error": f"{name} diverges from "
                                           f"fused at array {i}"}))
                raise SystemExit(1)
    # corruption must be caught by the fused lane too
    bad = blob.copy()
    bad[bad.nbytes // 2] ^= 0xFF
    try:
        native.scan_verify(bad, seed=0)
        print(json.dumps({"error": "fused scan missed corruption"}))
        raise SystemExit(1)
    except native.NativeError:
        pass
    return {"metric": "replay_pipeline_smoke", "entries": entries,
            "lanes": ["fused", "two-pass", "chunked", "streamed"],
            "ok": True}


def main() -> int:
    args = [a for a in sys.argv[1:]]
    if "--smoke" in args:
        print(json.dumps(smoke()))
        return 0
    entries = int(args[0]) if args else 500_000
    payload = int(args[1]) if len(args) > 1 else 256
    out = sweep(entries, payload)
    os.makedirs(_ART_DIR, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(_ART_DIR, f"replay_pipeline_{stamp}.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    out["artifact"] = os.path.relpath(path)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
