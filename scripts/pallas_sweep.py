"""On-chip sweep of raw-CRC kernel variants (task: tune the Pallas path).

Methodology notes (axon tunnel quirks discovered empirically):
- per-dispatch overhead is ~65-80 ms regardless of payload, and
  block_until_ready can return before remote completion; only a value
  fetch is a trustworthy sync point.
- loop-invariant code motion: a fori_loop whose body reads the same
  buffer computes ONE pass; the body must depend on the loop index.
  Here each iteration XORs the buffer with i (adds ~2x input HBM
  traffic, ~1 ms at 819 GB/s — negligible vs the matmul).

Usage: python scripts/pallas_sweep.py [K_ITERS] [N_ROWS_LOG2]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.ops.crc_device import (
    _from_bits32,
    _unpack_bits,
    contribution_matrix,
)

K = int(sys.argv[1]) if len(sys.argv) > 1 else 12
N = 1 << (int(sys.argv[2]) if len(sys.argv) > 2 else 20)
L = 384

rng = np.random.default_rng(0)
cnp = contribution_matrix(L)


def measure(name, fn, buf, k=K):
    """fn: [N, L] uint8 -> uint32 [N]; returns GB/s of input bytes."""

    @functools.partial(jax.jit, static_argnames=("kk",))
    def loop(b, kk):
        def body(i, acc):
            r = fn(b ^ i.astype(jnp.uint8))
            return acc ^ r[0] ^ r[-1]

        return jax.lax.fori_loop(0, kk, body, jnp.uint32(0))

    try:
        # warm with the SAME static k: a different k is a different
        # executable and its compile would land in the timed region
        int(loop(buf, k))
        t0 = time.perf_counter()
        int(loop(buf, k))
        dt = time.perf_counter() - t0
    except Exception as e:
        print(f"{name}: FAILED {type(e).__name__}: {str(e)[:160]}")
        return
    gbps = N * L * k / dt / 1e9
    print(f"{name}: {gbps:6.2f} GB/s  ({N*k/dt/1e6:7.1f}M rec/s, "
          f"{dt:.3f}s / {k} iters)", flush=True)


# -- variants ---------------------------------------------------------------

c8 = jnp.asarray(cnp)
cbf = jnp.asarray(cnp, jnp.bfloat16)


def xla_int8(buf):
    bits = _unpack_bits(buf)
    acc = jax.lax.dot_general(
        bits, c8, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return _from_bits32(acc & 1)


def xla_bf16(buf):
    bits = _unpack_bits(buf).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        bits, cbf, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return _from_bits32(acc.astype(jnp.int32) & 1)


def pallas_current(buf):
    from etcd_tpu.ops.crc_pallas import raw_crc_pallas
    return raw_crc_pallas(buf, c8)


def make_pallas_planes(tile, dtype):
    """Per-bit-plane dots in VMEM; no concatenate; optional bf16 MXU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # plane-major contribution: cp[k] is [L, 32] for bit k
    cp = cnp.reshape(L, 8, 32).transpose(1, 0, 2)  # [8, L, 32]
    if dtype == jnp.bfloat16:
        cpj = jnp.asarray(cp, jnp.bfloat16)
    else:
        cpj = jnp.asarray(cp, jnp.int8)

    def kernel(buf_ref, c_ref, out_ref):
        x = buf_ref[:].astype(jnp.int32) & 0xFF
        acc = None
        for k in range(8):
            bits = ((x >> k) & 1).astype(dtype)
            d = jax.lax.dot_general(
                bits, c_ref[k],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32
                if dtype == jnp.bfloat16 else jnp.int32)
            acc = d if acc is None else acc + d
        if dtype == jnp.bfloat16:
            acc = acc.astype(jnp.int32)
        out_ref[:] = acc & 1

    @jax.jit
    def run(buf):
        from jax.experimental import pallas as pl
        n = buf.shape[0]
        n_pad = (n + tile - 1) // tile * tile
        buf8 = jax.lax.bitcast_convert_type(
            jnp.pad(buf, ((0, n_pad - n), (0, 0))), jnp.int8)
        parity = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_pad, 32), jnp.int32),
            grid=(n_pad // tile,),
            in_specs=[
                pl.BlockSpec((tile, L), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((8, L, 32), lambda i: (0, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile, 32), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
        )(buf8, cpj)
        return _from_bits32(parity[:n])

    return run


def make_pallas_concat(tile):
    """Current kernel shape but parametrized tile."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cr = cnp.reshape(L, 8, 32).transpose(1, 0, 2).reshape(8 * L, 32)
    crj = jnp.asarray(cr, jnp.int8)

    def kernel(buf_ref, c_ref, out_ref):
        x = buf_ref[:].astype(jnp.int32) & 0xFF
        bits = jnp.concatenate(
            [((x >> k) & 1).astype(jnp.int8) for k in range(8)], axis=1)
        acc = jax.lax.dot_general(
            bits, c_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out_ref[:] = acc & 1

    @jax.jit
    def run(buf):
        n = buf.shape[0]
        n_pad = (n + tile - 1) // tile * tile
        buf8 = jax.lax.bitcast_convert_type(
            jnp.pad(buf, ((0, n_pad - n), (0, 0))), jnp.int8)
        parity = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_pad, 32), jnp.int32),
            grid=(n_pad // tile,),
            in_specs=[
                pl.BlockSpec((tile, L), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((8 * L, 32), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile, 32), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
        )(buf8, crj)
        return _from_bits32(parity[:n])

    return run


def main():
    print(f"backend={jax.default_backend()} N={N} L={L} K={K}",
          flush=True)
    buf = jax.device_put(
        rng.integers(0, 256, size=(N, L), dtype=np.uint8))
    buf.block_until_ready()

    # correctness spot check once
    from etcd_tpu.crc.crc32c import raw_update
    small = np.asarray(buf[:64])
    exp = np.asarray([raw_update(0, r.tobytes()) for r in small],
                     dtype=np.uint32)
    got = np.asarray(xla_int8(jnp.asarray(small)))
    assert (got == exp).all(), "xla_int8 wrong"

    measure("xla_int8        ", xla_int8, buf)
    measure("xla_bf16        ", xla_bf16, buf)
    measure("pallas_current  ", pallas_current, buf)
    for tile in (512, 1024, 2048):
        measure(f"pallas_cat t{tile:4d}",
                make_pallas_concat(tile), buf)
    for tile in (512, 1024, 2048):
        measure(f"pallas_pl8 t{tile:4d}",
                make_pallas_planes(tile, jnp.int8), buf)
    for tile in (1024, 2048):
        measure(f"pallas_bf16 t{tile:3d}",
                make_pallas_planes(tile, jnp.bfloat16), buf)


if __name__ == "__main__":
    main()
