"""Bounded device-init probe: forks a child that initializes the jax
backend and prints the device list; the parent gives it a deadline.

The TCP-level relay_probe.py can print ``up`` while the tunnel is
wedged (PALLAS_NOTES.md "Operational hazard": a stuck session makes
every subsequent ``jax.devices()`` hang in ANY process).  This probe
answers the question that matters before committing chip time: can a
fresh process actually establish a session right now?

    python scripts/device_probe.py [TIMEOUT_S]     (default 120)

Prints one JSON line {"outcome": "ok"|"hang"|"error", ...}; exit 0
only on "ok".
"""

import json
import multiprocessing as mp
import sys
import time


def _probe(q):
    try:
        import jax

        q.put(("ok", ",".join(str(d) for d in jax.devices()),
               jax.default_backend()))
    except Exception as e:  # pragma: no cover - env specific
        q.put(("error", repr(e)[:200], None))


def main() -> int:
    budget = float(sys.argv[1]) if len(sys.argv) > 1 else 120.0
    mp.set_start_method("spawn")
    q = mp.Queue()
    p = mp.Process(target=_probe, args=(q,), daemon=True)
    t0 = time.time()
    p.start()
    p.join(timeout=budget)
    if p.is_alive():
        p.terminate()
        p.join(5)
        if p.is_alive():
            # a child stuck in uninterruptible native init survives
            # SIGTERM; it must not outlive the probe holding (or
            # queueing for) the single-session tunnel
            p.kill()
            p.join(5)
        print(json.dumps({"outcome": "hang", "budget_s": budget}))
        return 1
    try:
        # q.empty() right after join() races the queue's feeder
        # thread — a healthy probe could read as dead and the watcher
        # would skip an open chip window; block briefly instead
        kind, detail, backend = q.get(timeout=10)
    except Exception:
        print(json.dumps({"outcome": "error",
                          "detail": "child died silently"}))
        return 1
    print(json.dumps({"outcome": kind, "devices": detail,
                      "backend": backend,
                      "seconds": round(time.time() - t0, 1)}))
    return 0 if kind == "ok" and backend == "tpu" else 1


if __name__ == "__main__":
    sys.exit(main())
