"""Cluster doctor: one CLI that turns the observability plane into
a single human-readable health report (PR 17).

Feed it supervisor merged-obs URLs (role-split topology, PR 15) or
flat node URLs (single-process dist nodes) — it auto-detects which
it got via ``GET /mraft/roles`` and harvests, per host:

  - the merged/flat metrics snapshot (``/mraft/obs``) — role
    liveness and the profiler's stage×domain sample attribution;
  - the time-series ring (``/mraft/obs/timeseries``) — the last
    ~2 minutes of windowed deltas, pooled cross-host into the
    standard windowed row (acked/s and reads/s over 10 s, RTT p99
    over 60 s, shed rate);
  - the SLO verdict (``/mraft/obs/slo``) — merged worst-of across
    hosts with per-objective burn rates;
  - the flight ring (``/mraft/obs/flight``, flat nodes only) —
    span/frame counts plus cross-node clock offsets recovered by
    scripts/trace_stitch.py's NTP-style frame-quad alignment.

A host that fails to answer is reported DOWN and skipped — the
doctor never turns one dead process into a harvest error, same
contract as the supervisor's merged exposition.

  JAX_PLATFORMS=cpu python scripts/doctor.py URL [URL ...]
  JAX_PLATFORMS=cpu python scripts/doctor.py --json URL [URL ...]
  JAX_PLATFORMS=cpu python scripts/doctor.py --smoke

``--smoke`` spawns a 3-host role-split family (the dist_bench
helpers), drives a small write load, runs the full harvest against
the supervisors' merged planes, asserts roles are up with nonzero
windowed rates and an SLO verdict, and prints DOCTOR SMOKE CLEAN.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from etcd_tpu.obs import slo as _slo  # noqa: E402
from etcd_tpu.obs import timeseries as _timeseries  # noqa: E402


def _get_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _get_bytes(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def harvest_host(base: str, timeout: float = 5.0) -> dict:
    """Everything one host's obs plane offers, each endpoint
    independently best-effort."""
    host: dict = {"url": base, "up": False}
    try:
        host["roles"] = _get_json(base + "/mraft/roles",
                                  timeout)["roles"]
        host["kind"] = "supervisor"
    except Exception:
        host["kind"] = "node"
    for key, sub in (("obs", "/mraft/obs"),
                     ("timeseries", "/mraft/obs/timeseries"),
                     ("slo", "/mraft/obs/slo")):
        try:
            host[key] = _get_json(base + sub, timeout)
            host["up"] = True
        except Exception:
            pass
    if host["kind"] == "node":
        # flat nodes carry their own flight ring; supervisors don't
        # (each role process owns its ring — harvest those directly)
        try:
            host["flight"] = _get_bytes(base + "/mraft/obs/flight",
                                        timeout)
        except Exception:
            pass
    return host


def collect(urls: list[str], timeout: float = 5.0) -> dict:
    hosts = [harvest_host(u, timeout) for u in urls]
    ts_snaps = [h["timeseries"] for h in hosts
                if "timeseries" in h]
    verdicts = [h["slo"] for h in hosts if "slo" in h]
    rep: dict = {
        "t": time.time(),
        "hosts": hosts,
        "windowed": (_timeseries.windowed_summary(ts_snaps)
                     if ts_snaps else None),
        "slo": (_slo.merge_verdicts(verdicts)
                if verdicts else None),
    }
    rep["profile"] = profile_table(hosts)
    rep["clocks"] = clock_offsets(hosts)
    return rep


def profile_table(hosts: list[dict], top: int = 8) -> list[dict]:
    """Top stage×domain×role rows off the always-on sampling
    profiler's etcd_profile_samples_total — where the threads
    actually were, merged across every harvested host."""
    agg: dict[tuple, float] = {}
    for h in hosts:
        obs = h.get("obs") or {}
        fams = obs.get("families", obs)  # merged vs flat shape
        for s in (fams.get("etcd_profile_samples_total") or
                  {}).get("samples", []):
            lb = s.get("labels", {})
            k = (lb.get("stage", "-"), lb.get("domain", "-"),
                 lb.get("role", "-"))
            agg[k] = agg.get(k, 0.0) + s.get("value", 0.0)
    total = sum(agg.values())
    rows = []
    for (stage, domain, role), n in sorted(agg.items(),
                                           key=lambda kv: -kv[1]):
        rows.append({"stage": stage, "domain": domain,
                     "role": role, "samples": int(n),
                     "share": round(n / total, 4) if total else 0.0})
    return rows[:top]


def clock_offsets(hosts: list[dict]) -> dict | None:
    """Cross-node clock offsets recovered from the flight rings via
    trace_stitch's frame-quad alignment — the same offsets the
    stitcher subtracts to land every span on one clock."""
    import trace_stitch

    dumps = [h["flight"] for h in hosts if h.get("flight")]
    if len(dumps) < 2:
        return None
    td = tempfile.mkdtemp(prefix="doctor_flight_")
    try:
        paths = []
        for i, body in enumerate(dumps):
            p = os.path.join(td, f"flight_{i}.json")
            with open(p, "wb") as f:
                f.write(body)
            paths.append(p)
        nodes = trace_stitch.load_dumps(paths)
        off = trace_stitch.align(nodes)
        return {f"slot{slot}/{role}": round(v * 1e3, 3)
                for (slot, role), v in sorted(off.items())}
    except Exception as e:
        return {"error": str(e)}
    finally:
        shutil.rmtree(td, ignore_errors=True)


def render(rep: dict) -> str:
    """The human-readable report."""
    L: list[str] = []
    L.append("== cluster doctor "
             + time.strftime("%Y-%m-%dT%H:%M:%SZ",
                             time.gmtime(rep["t"])) + " ==")
    up = sum(1 for h in rep["hosts"] if h["up"])
    L.append(f"hosts: {up}/{len(rep['hosts'])} answering")
    for h in rep["hosts"]:
        mark = "up" if h["up"] else "DOWN"
        L.append(f"  {h['url']} [{h['kind']}] {mark}")
        for role, info in sorted((h.get("roles") or {}).items()):
            alive = "up" if info.get("up") else "STALE"
            extra = ""
            if not info.get("up") and "stale_s" in info:
                extra = f" ({info['stale_s']:.1f}s stale)"
            L.append(f"    role {role:<12} {alive}{extra}")
    w = rep.get("windowed")
    if w:
        L.append("windowed (time-series rings):")
        L.append(f"  acked/s (10s):      {w['acked_per_s_10s']}")
        L.append(f"  reads/s (10s):      {w['reads_per_s_10s']}")
        L.append(f"  ack p99 ms (60s):   {w['ack_rtt_p99_ms_60s']}")
        L.append(f"  read p99 ms (60s):  {w['read_rtt_p99_ms_60s']}")
        L.append(f"  shed rate (60s):    {w['shed_rate_60s']}")
    s = rep.get("slo")
    if s:
        L.append(f"slo: verdict={s['verdict']}"
                 + (f" worst={s['worst']}" if s.get("worst")
                    else ""))
        for name, o in sorted(s.get("objectives", {}).items()):
            L.append(f"  {name:<14} burn={o['burn_rate']:<8.3f} "
                     f"{'ok' if o.get('ok') else 'BURNING'}"
                     f" (target {o['target']}, "
                     f"{o.get('samples', 0)} samples)")
    if rep.get("profile"):
        L.append("profiler (top stage x domain x role by samples):")
        for r in rep["profile"]:
            L.append(f"  {r['share'] * 100:5.1f}%  "
                     f"stage={r['stage']} domain={r['domain']} "
                     f"role={r['role']} ({r['samples']})")
    c = rep.get("clocks")
    if c:
        L.append("clock offsets vs reference (ms, flight-ring "
                 "frame quads):")
        for k, v in c.items():
            L.append(f"  {k:<20} {v}")
    return "\n".join(L)


# -- smoke: spawn a role family and doctor it -------------------------------


def smoke() -> None:
    import http.client

    import dist_bench as db
    from etcd_tpu.server.distserver import pack_requests
    from etcd_tpu.wire.requests import Request

    m, shards = 3, 2
    peer_base = db.free_port_block(m * shards)
    client_base = db.free_port_block(3 * m)
    urls = [f"http://127.0.0.1:{peer_base + i}" for i in range(m)]
    tmp = tempfile.mkdtemp()
    procs = [db.spawn_roles(tmp, s, urls, client_base + s, shards)
             for s in range(m)]
    try:
        for p in procs:
            db.wait_ready(p)
        # drive a small write load so the rings and the SLO layer
        # have something to window over
        c = http.client.HTTPConnection("127.0.0.1", client_base,
                                       timeout=60)
        # warm until the shard leaders elect (verdicts are final,
        # so the counted load only starts once a write acks)
        for _ in range(200):
            n, nerr = db._propose(c, pack_requests([Request(
                method="PUT", id=(1 << 50) + 1,
                path="/warm/k", val="v")]), "binary")
            if n - nerr == 1:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("role family never acked a write")
        # fresh ids per batch until 200 ack — the warm write only
        # proves ONE shard's leader; a batch spanning namespaces can
        # land on a shard still electing, and verdicts are final.
        # 200 over 90 s is load enough to window over: one
        # sequential conn pays full round latency per batch (~2-7 s
        # each on a busy 1-core host), and the smoke gates plumbing,
        # not throughput
        acked, nid, deadline = 0, 0, time.monotonic() + 90
        while acked < 200 and time.monotonic() < deadline:
            reqs = [Request(method="PUT", id=nid + j + 1,
                            path=f"/d{(nid + j) % 16}/k", val="v")
                    for j in range(50)]
            nid += 50
            n, nerr = db._propose(c, pack_requests(reqs), "binary")
            acked += n - nerr
            if nerr:
                time.sleep(0.2)
        c.close()
        assert acked >= 200, acked
        # let the 1 s scrape/step loops take at least two steps
        time.sleep(2.5)

        sup_urls = [f"http://127.0.0.1:{client_base + 2 * m + i}"
                    for i in range(m)]
        rep = collect(sup_urls)
        print(render(rep), flush=True)

        assert all(h["up"] and h["kind"] == "supervisor"
                   for h in rep["hosts"]), rep["hosts"]
        for h in rep["hosts"]:
            roles = h["roles"]
            for want in ("ingest", "worker", "shard0", "shard1",
                         "supervisor"):
                assert roles.get(want, {}).get("up"), (want, roles)
        assert rep["windowed"]["acked_per_s_10s"] > 0, \
            rep["windowed"]
        assert rep["slo"]["verdict"] in ("ok", "burning"), \
            rep["slo"]
        assert "write_ack_p99" in rep["slo"]["objectives"], \
            rep["slo"]
        assert rep["profile"], "no profiler samples harvested"
        print("DOCTOR SMOKE CLEAN", flush=True)
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("urls", nargs="*",
                    help="supervisor merged-obs or flat node URLs")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report dict instead of the "
                         "rendered text")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--smoke", action="store_true",
                    help="self-contained 3-host role-family check "
                         "for scripts/test")
    args = ap.parse_args()
    if args.smoke:
        smoke()
        return
    if not args.urls:
        ap.error("need at least one URL (or --smoke)")
    rep = collect(args.urls, timeout=args.timeout)
    if args.json:
        # flight bodies are bytes and huge — the JSON view carries
        # everything else
        for h in rep["hosts"]:
            h.pop("flight", None)
        print(json.dumps(rep, indent=1, sort_keys=True))
    else:
        print(render(rep))


if __name__ == "__main__":
    main()
