"""Generate the committed reference-layout WAL + snapshot fixture
(tests/fixtures/refdir) and print its SHA256 pins.

No Go toolchain exists in this image, so the fixture cannot be
emitted by the reference binary itself; it is hand-assembled to the
reference's exact on-disk layout — gogoproto field order pinned by
the golden bytes in tests/test_wire.py, file naming
%016x-%016x.{wal,snap} (wal/util.go:77-88, snap/snapshotter.go:47),
int64-LE length framing (wal/decoder.go:30-35), rolling CRC chain
seeded 0 with crcType records across cuts (wal/wal.go:184-237), and
snappb whole-file CRC (snap/snapshotter.go:39-60).  The fixture is
deterministic: regenerating must reproduce the pinned hashes.
"""

import hashlib
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from etcd_tpu.snap import Snapshotter  # noqa: E402
from etcd_tpu.wal import WAL  # noqa: E402
from etcd_tpu.wire import Entry, HardState, Snapshot  # noqa: E402
from etcd_tpu.wire.requests import Info, Request  # noqa: E402

FIXDIR = os.path.join(os.path.dirname(__file__), "..", "tests",
                      "fixtures", "refdir")

# Deterministic content: 12 committed PUTs in two WAL segments plus a
# store snapshot at entry 8 (the mid-stream cut exercises the chained
# crcType record the reference writes on every segment roll).
NODE_ID = 0x1234567890ABCDEF


def main() -> None:
    shutil.rmtree(FIXDIR, ignore_errors=True)
    os.makedirs(os.path.join(FIXDIR, "snap"))
    waldir = os.path.join(FIXDIR, "wal")

    w = WAL.create(waldir, Info(id=NODE_ID).marshal())
    # open-at-0 streams start at the dummy entry 0, the reference's
    # raft-log seed shape (wal/wal_test.go:163's ents begin {0, 0})
    w.save(HardState(term=1, vote=1, commit=0),
           [Entry(index=0, term=0)])
    for i in range(1, 9):
        r = Request(method="PUT", id=i, path=f"/fix/k{i}",
                    val=f"v{i}")
        w.save(HardState(term=1, vote=1, commit=i),
               [Entry(index=i, term=1, data=r.marshal())])
    w.cut()  # segment roll: chained crc record into 0000..0008.wal
    for i in range(9, 13):
        r = Request(method="PUT", id=i, path=f"/fix/k{i}",
                    val=f"v{i}")
        w.save(HardState(term=2, vote=1, commit=i),
               [Entry(index=i, term=2, data=r.marshal())])
    w.close()

    # store snapshot at index 8: the tree the first 8 PUTs build,
    # in the reference's store.Save() JSON shape
    from etcd_tpu.store import Store
    from etcd_tpu.server.server import apply_request_to_store

    st = Store()
    for i in range(1, 9):
        apply_request_to_store(st, Request(
            method="PUT", id=i, path=f"/fix/k{i}", val=f"v{i}"))
    Snapshotter(os.path.join(FIXDIR, "snap")).save_snap(Snapshot(
        index=8, term=1, data=st.save()))

    pins = {}
    for root, _dirs, files in os.walk(FIXDIR):
        for f in sorted(files):
            p = os.path.join(root, f)
            rel = os.path.relpath(p, FIXDIR)
            pins[rel] = hashlib.sha256(
                open(p, "rb").read()).hexdigest()
    print(json.dumps(pins, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
