"""Packed inter-role handoff frames for the compartmentalized
serving topology (PR 15).

The role split (server/roles.py) moves client ingest, apply/watch
fanout, and group-sharded consensus into separate processes.  The
handoff between them must not re-spend PR 14's wire winnings on
serialization, so every hop uses the same fixed-table + blob style as
``wire/distmsg.py`` (DGB3) and ``wire/clientmsg.py`` (DCB1): numpy
``frombuffer`` views over length tables, one-pass blob slicing, and
typed ``FrameError`` totality (mutation fuzz in tests/test_roles.py).

Frame = 12-byte header + kind-specific sections:

  header:    magic "DRH1" | kind u8 | flags u8 | rsvd u16 | count u32

  FWD_REQ:   opflags [count] u8 + pad-to-4 + rlens [count] i32
             + concatenated Request.marshal blobs.  The op flag
             carries ``Request.serializable`` — a LOCAL-ONLY field
             the version-stable marshal form deliberately omits, but
             which must survive the ingest -> shard hop or every
             replica-local read silently upgrades to linearizable.
             Header flags pick the reply shape (below).
  FWD_ACKS:  sparse errs only (u32 n_errs + (idx i32, code i32,
             mlen i32) rows + utf-8 messages) — the write-batch
             reply; all-ok costs 16 bytes.
  FWD_VALS:  vlens [count] i32 (-1 = absent/error) + sparse errs +
             value blobs + message blobs — the read-batch reply.
  FWD_RESP:  one fixed 72-byte event row per op + a single blob
             stream — the full-fidelity reply for coalesced single
             client ops (the front door needs whole v2 events, not
             just values).  Rare shapes (directory listings, TTL'd
             prev nodes) ride a per-op JSON fallback flag; the hot
             flat event never touches JSON.
  COMMIT:    seq u64 + groups [count] i32 + gindex [count] i64 +
             rlens [count] i32 + concatenated entry payloads — the
             shard -> apply-worker committed stream (shared-memory
             ring records, server/shmring.py).  ``seq`` numbers
             frames per ring so a consumer detects dropped frames as
             a gap instead of silently missing events.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from .distmsg import FrameError, _view_i32
from .schema import DRH1, check_bound
from ..store.event import Event, NodeExtern

# layout constants come from the declarative schema (wire/schema.py)
_MAGIC = DRH1.magic
_HDR = DRH1.header_struct()

_KINDS = DRH1.kind_values()
KIND_FWD_REQ = _KINDS["KIND_FWD_REQ"]
KIND_FWD_ACKS = _KINDS["KIND_FWD_ACKS"]
KIND_FWD_VALS = _KINDS["KIND_FWD_VALS"]
KIND_FWD_RESP = _KINDS["KIND_FWD_RESP"]
KIND_COMMIT = _KINDS["KIND_COMMIT"]

# FWD_REQ header flags: requested reply shape
REPLY_EVENTS = 0       # FWD_RESP (full v2 events)
REPLY_ACKS = 0x01      # FWD_ACKS (write batch: error-sparse)
REPLY_VALS = 0x02      # FWD_VALS (read batch: leaf values)

# FWD_REQ per-op flags
OP_SERIALIZABLE = 0x01

#: one sparse error row: op index i32, error code i32, msg len i32
_ERR = struct.Struct(DRH1.structs["_ERR"])

#: one FWD_RESP event row (72 bytes):
#: code i32 | action u8 | flags u8 | rsvd u16 | etcd_index i64 |
#: mod i64 | created i64 | pmod i64 | pcreated i64 | expiration f64 |
#: ttl i32 | klen i32 | vlen i32 | pvlen i32
_EVT = struct.Struct(DRH1.structs["_EVT"])

F_ERR = 0x01        # error row: code + cause (klen bytes), index
F_HAS_NODE = 0x02
F_HAS_PREV = 0x04
F_DIR = 0x08        # node.dir
F_PDIR = 0x10       # prev_node.dir
F_JSON = 0x20       # fallback: klen bytes of event-dict JSON
F_HAS_EXP = 0x40    # expiration field is meaningful

_ACTIONS = ("get", "create", "set", "update", "delete",
            "compareAndSwap", "compareAndDelete", "expire")
_ACTION_IDX = {a: i for i, a in enumerate(_ACTIONS)}


def _parse_header(data) -> tuple[int, int, int]:
    """Returns (kind, flags, count); raises FrameError."""
    if len(data) < _HDR.size:
        raise FrameError("short role frame")
    magic, kind, flags, _rsvd, count = _HDR.unpack_from(data)
    if magic != _MAGIC:
        raise FrameError("bad role frame magic")
    # the header count sizes every downstream table view and the
    # fwd_acks return value — cap it before anything allocates (it
    # used to flow through unpack_fwd_acks unchecked)
    check_bound("drh1.count", count)
    return kind, flags, count


def _view_i64(data, pos: int, n: int) -> tuple[np.ndarray, int]:
    end = pos + 8 * n
    if end > len(data):
        raise FrameError("truncated i64 section")
    return np.frombuffer(data, "<i8", count=n, offset=pos), end


def _view_u8(data, pos: int, n: int) -> tuple[np.ndarray, int]:
    end = pos + n
    if end > len(data):
        raise FrameError("truncated u8 section")
    return np.frombuffer(data, np.uint8, count=n, offset=pos), end


def _lens_blobs(blobs: list[bytes]) -> tuple[bytes, bytes]:
    lens = np.fromiter(map(len, blobs), "<i4", count=len(blobs))
    return lens.tobytes(), b"".join(blobs)


def _slice_blobs(data, pos: int, lens: np.ndarray) -> list[bytes]:
    if lens.size and int(lens.min()) < 0:
        raise FrameError("negative blob length")
    if lens.size:
        check_bound("drh1.blob_len", int(lens.max()))
    # int64 running ends: adversarial i32 lens must overflow into the
    # bounds check, never wrap into a wrong slice
    ends = lens.cumsum(dtype=np.int64)
    total = int(ends[-1]) if lens.size else 0
    if pos + total > len(data):
        raise FrameError("truncated blob section")
    out = []
    a = pos
    for b in ends.tolist():
        out.append(bytes(data[a:pos + b]))
        a = pos + b
    return out


# -- FWD_REQ ----------------------------------------------------------------


def pack_fwd_request(blobs: list[bytes], opflags: list[int],
                     reply: int = REPLY_EVENTS) -> bytes:
    """``blobs``: Request.marshal per op; ``opflags``: per-op flag
    byte (OP_SERIALIZABLE)."""
    count = len(blobs)
    if len(opflags) != count:
        raise ValueError("opflags/blobs length mismatch")
    lens, blob = _lens_blobs(blobs)
    pad = b"\x00" * (-(_HDR.size + count) % 4)
    return b"".join((
        _HDR.pack(_MAGIC, KIND_FWD_REQ, reply, 0, count),
        bytes(bytearray(opflags)), pad, lens, blob))


def unpack_fwd_request(data) -> tuple[list[bytes], np.ndarray, int]:
    """Returns (request blobs, [count] u8 opflags view, reply
    shape)."""
    kind, flags, count = _parse_header(data)
    if kind != KIND_FWD_REQ:
        raise FrameError(f"kind {kind} != fwd_req")
    opflags, pos = _view_u8(data, _HDR.size, count)
    pos += -pos % 4
    rlens, pos = _view_i32(data, pos, count)
    return _slice_blobs(data, pos, rlens), opflags, flags


# -- sparse errs (shared by FWD_ACKS / FWD_VALS) ----------------------------


def _pack_errs(errs: dict[int, tuple[int, str]]
               ) -> tuple[bytes, list[bytes]]:
    lead = bytearray(4 + _ERR.size * len(errs))
    struct.pack_into("<I", lead, 0, len(errs))
    pos = 4
    msgs = []
    for idx in sorted(errs):
        code, msg = errs[idx]
        mb = msg.encode()
        _ERR.pack_into(lead, pos, idx, code, len(mb))
        pos += _ERR.size
        msgs.append(mb)
    return bytes(lead), msgs


def _unpack_errs(data, pos: int, count: int
                 ) -> tuple[list[tuple[int, int, int]], int]:
    if pos + 4 > len(data):
        raise FrameError("truncated errs table")
    (n_errs,) = struct.unpack_from("<I", data, pos)
    pos += 4
    if n_errs > count:
        raise FrameError(f"errs {n_errs} > ops {count}")
    end = pos + n_errs * _ERR.size
    if end > len(data):
        raise FrameError("truncated errs table")
    rows = []
    for _ in range(n_errs):
        idx, code, mlen = _ERR.unpack_from(data, pos)
        pos += _ERR.size
        if idx < 0 or idx >= count:
            raise FrameError("errs index out of range")
        check_bound("drh1.msg_len", mlen)
        rows.append((idx, code, mlen))
    return rows, pos


def _slice_msgs(data, pos: int, rows) -> dict[int, tuple[int, str]]:
    errs: dict[int, tuple[int, str]] = {}
    buf = memoryview(data)
    for idx, code, mlen in rows:
        if pos + mlen > len(data):
            raise FrameError("truncated errs message")
        try:
            errs[idx] = (code, str(buf[pos:pos + mlen], "utf-8"))
        except UnicodeDecodeError:
            raise FrameError("errs message not utf-8") from None
        pos += mlen
    return errs


# -- FWD_ACKS ---------------------------------------------------------------


def pack_fwd_acks(count: int,
                  errs: dict[int, tuple[int, str]]) -> bytes:
    lead, msgs = _pack_errs(errs)
    return b"".join((_HDR.pack(_MAGIC, KIND_FWD_ACKS, 0, 0, count),
                     lead, *msgs))


def unpack_fwd_acks(data) -> tuple[int, dict[int, tuple[int, str]]]:
    kind, _flags, count = _parse_header(data)
    if kind != KIND_FWD_ACKS:
        raise FrameError(f"kind {kind} != fwd_acks")
    rows, pos = _unpack_errs(data, _HDR.size, count)
    return count, _slice_msgs(data, pos, rows)


# -- FWD_VALS ---------------------------------------------------------------


def pack_fwd_vals(vals: list[bytes | str | None],
                  errs: dict[int, tuple[int, str]]) -> bytes:
    lead, msgs = _pack_errs(errs)
    lens = []
    parts = []
    for v in vals:
        if v is None:
            lens.append(-1)
            continue
        b = v if type(v) is bytes else str(v).encode()
        parts.append(b)
        lens.append(len(b))
    return b"".join((
        _HDR.pack(_MAGIC, KIND_FWD_VALS, 0, 0, len(vals)),
        np.asarray(lens, "<i4").tobytes(), lead, *parts, *msgs))


def unpack_fwd_vals(data) -> tuple[list[bytes | None],
                                   dict[int, tuple[int, str]]]:
    kind, _flags, count = _parse_header(data)
    if kind != KIND_FWD_VALS:
        raise FrameError(f"kind {kind} != fwd_vals")
    vlens, pos = _view_i32(data, _HDR.size, count)
    if count and int(vlens.min()) < -1:
        raise FrameError("bad value length")
    if count:
        # -1 rows mean "absent" and are legal — cap the largest
        # actual value length only
        check_bound("drh1.val_len", max(0, int(vlens.max())))
    rows, pos = _unpack_errs(data, pos, count)
    total = int(np.maximum(vlens, 0).sum(dtype=np.int64))
    if pos + total > len(data):
        raise FrameError("truncated value blob")
    vals: list[bytes | None] = []
    a = pos
    for ln in vlens.tolist():
        if ln < 0:
            vals.append(None)
        else:
            vals.append(bytes(data[a:a + ln]))
            a += ln
    return vals, _slice_msgs(data, a, rows)


# -- FWD_RESP ---------------------------------------------------------------


def _node_fits(n: NodeExtern | None) -> bool:
    """The flat row carries (key, value, dir, ttl, expiration, mod,
    created); listings (``nodes``) need the JSON fallback."""
    return n is None or not n.nodes


def _enc(s: str | None) -> bytes:
    return b"" if s is None else s.encode()


def pack_fwd_response(results: list) -> bytes:
    """``results``: per op, either a store ``Event`` (with
    ``etcd_index`` set) or an exception (EtcdError-shaped: uses
    ``error_code``/``cause``/``index`` when present)."""
    count = len(results)
    rows = bytearray(_EVT.size * count)
    blobs: list[bytes] = []
    pos = 0
    for x in results:
        code = 0
        action = 0
        flags = 0
        eidx = mod = created = pmod = pcreated = 0
        exp = 0.0
        ttl = 0
        klen = vlen = pvlen = 0
        if isinstance(x, Exception):
            flags = F_ERR
            code = getattr(x, "error_code", 300)
            eidx = getattr(x, "index", 0)
            cause = getattr(x, "cause", None)
            b = (cause if cause is not None else str(x)).encode()
            blobs.append(b)
            klen = len(b)
            vlen = pvlen = -1
        else:
            ev = x
            eidx = ev.etcd_index
            n, p = ev.node, ev.prev_node
            if (ev.action in _ACTION_IDX and _node_fits(n)
                    and _node_fits(p)
                    and (p is None or (p.ttl == 0
                                       and p.expiration is None
                                       and (n is None
                                            or p.key == n.key)))):
                action = _ACTION_IDX[ev.action]
                if n is not None:
                    flags |= F_HAS_NODE
                    if n.dir:
                        flags |= F_DIR
                    if n.expiration is not None:
                        flags |= F_HAS_EXP
                        exp = float(n.expiration)
                    ttl = n.ttl
                    mod, created = n.modified_index, n.created_index
                    kb = _enc(n.key)
                    blobs.append(kb)
                    klen = len(kb)
                    if n.value is None:
                        vlen = -1
                    else:
                        vb = _enc(n.value)
                        blobs.append(vb)
                        vlen = len(vb)
                else:
                    vlen = -1
                if p is not None:
                    flags |= F_HAS_PREV
                    if p.dir:
                        flags |= F_PDIR
                    pmod, pcreated = (p.modified_index,
                                      p.created_index)
                    if p.value is None:
                        pvlen = -1
                    else:
                        pb = _enc(p.value)
                        blobs.append(pb)
                        pvlen = len(pb)
                else:
                    pvlen = -1
            else:
                # rare shape (listing / TTL'd prev / alien action):
                # whole-event JSON, still one blob in the stream
                flags = F_JSON
                b = json.dumps(ev.to_dict()).encode()
                blobs.append(b)
                klen = len(b)
                vlen = pvlen = -1
        _EVT.pack_into(rows, pos, code, action, flags, 0, eidx,
                       mod, created, pmod, pcreated, exp, ttl,
                       klen, vlen, pvlen)
        pos += _EVT.size
    return b"".join((
        _HDR.pack(_MAGIC, KIND_FWD_RESP, 0, 0, count),
        bytes(rows), *blobs))


def unpack_fwd_response(data) -> list:
    """Returns per-op ``Event`` | ``(code, cause, index)`` error
    tuples (the caller rebuilds its typed error)."""
    kind, _flags, count = _parse_header(data)
    if kind != KIND_FWD_RESP:
        raise FrameError(f"kind {kind} != fwd_resp")
    pos = _HDR.size
    if pos + _EVT.size * count > len(data):
        raise FrameError("truncated event rows")
    out: list = []
    cur = pos + _EVT.size * count
    buf = memoryview(data)

    def take(n: int) -> bytes:
        nonlocal cur
        if n < 0 or cur + n > len(data):
            raise FrameError("truncated event blob")
        b = bytes(buf[cur:cur + n])
        cur += n
        return b

    for i in range(count):
        (code, action, flags, _r, eidx, mod, created, pmod,
         pcreated, exp, ttl, klen, vlen, pvlen) = _EVT.unpack_from(
            data, pos + i * _EVT.size)
        if flags & F_ERR:
            try:
                cause = take(klen).decode()
            except UnicodeDecodeError:
                raise FrameError("error cause not utf-8") from None
            out.append((code, cause, eidx))
            continue
        try:
            if flags & F_JSON:
                try:
                    ev = Event.from_dict(json.loads(take(klen)))
                except (ValueError, KeyError, TypeError):
                    raise FrameError("bad event json") from None
                ev.etcd_index = eidx
                out.append(ev)
                continue
            if action >= len(_ACTIONS):
                raise FrameError("bad event action")
            node = prev = None
            if flags & F_HAS_NODE:
                key = take(klen).decode()
                val = None if vlen < 0 else take(vlen).decode()
                node = NodeExtern(
                    key=key, value=val, dir=bool(flags & F_DIR),
                    expiration=exp if flags & F_HAS_EXP else None,
                    ttl=ttl, modified_index=mod,
                    created_index=created)
            if flags & F_HAS_PREV:
                pval = None if pvlen < 0 else take(pvlen).decode()
                prev = NodeExtern(
                    key=node.key if node is not None else "",
                    value=pval, dir=bool(flags & F_PDIR),
                    modified_index=pmod, created_index=pcreated)
        except UnicodeDecodeError:
            raise FrameError("event text not utf-8") from None
        out.append(Event(action=_ACTIONS[action], node=node,
                         prev_node=prev, etcd_index=eidx))
    return out


# -- COMMIT -----------------------------------------------------------------


def pack_commit(seq: int, rows: list[tuple[int, int, bytes]]
                ) -> bytes:
    """``rows``: (group, gindex, payload) per committed entry."""
    count = len(rows)
    groups = np.fromiter((r[0] for r in rows), "<i4", count=count)
    gidx = np.fromiter((r[1] for r in rows), "<i8", count=count)
    lens = np.fromiter((len(r[2]) for r in rows), "<i4",
                       count=count)
    return b"".join((
        _HDR.pack(_MAGIC, KIND_COMMIT, 0, 0, count),
        struct.pack("<Q", seq),
        groups.tobytes(), gidx.tobytes(), lens.tobytes(),
        *(r[2] for r in rows)))


def unpack_commit(data) -> tuple[int, np.ndarray, np.ndarray,
                                 list[bytes]]:
    """Returns (seq, [count] group view, [count] gindex view,
    payload blobs)."""
    kind, _flags, count = _parse_header(data)
    if kind != KIND_COMMIT:
        raise FrameError(f"kind {kind} != commit")
    pos = _HDR.size
    if pos + 8 > len(data):
        raise FrameError("truncated commit seq")
    (seq,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    groups, pos = _view_i32(data, pos, count)
    gidx, pos = _view_i64(data, pos, count)
    rlens, pos = _view_i32(data, pos, count)
    return seq, groups, gidx, _slice_blobs(data, pos, rlens)
