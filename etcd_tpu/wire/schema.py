"""Declarative wire-frame schemas — the single source of truth for
every binary layout the serving path speaks (PR 19).

Five hand-rolled formats cross process and host boundaries: DGB2/DGB3
peer frames (``wire/distmsg.py``), the DCB1 client protocol
(``wire/clientmsg.py``), DRH1 role handoff (``wire/rolemsg.py``), the
gogoproto codec (``wire/proto.py``), and the SRG1 shm segment layout
(``server/shmring.py``).  Each used to carry its magic, struct format
strings, flag bits, and plausibility caps as module-private literals
maintained by hand in marshal/unmarshal pairs.  This module makes the
layouts first-class data:

  * ``FrameSchema`` declares magic, the header struct format with
    named fields, frame kinds with their ordered sections, flag bits
    mapped to the optional trailing section they gate, and — for the
    fixed-offset SRG1 header — the field offset table.
  * ``Bound`` annotates every wire length/count field with its
    plausibility cap (the ``implausible trace count`` guard that
    existed for exactly one field pre-PR-19, made total) and the
    parse scope expected to enforce it.  ``check_bound`` is the one
    enforcement call sites use; the wire-bounds checker
    (analysis/wirebounds.py) closes the vocabulary: every declared
    bound must be checked in its scope, and every checked name must
    be declared here.
  * The parser modules import their structs/magic/constants FROM this
    module; the schema-drift checker (analysis/schemadrift.py) fails
    lint on a locally re-declared layout literal and on
    marshal/unmarshal asymmetry against the declared sections.
  * The schema-driven fuzzer (scripts/wire_fuzz.py) generates its
    mutations — truncation at every boundary, flag flips, count-field
    extremes, signed overflows — from these declarations, asserting
    every failure is the format's typed error.

Grammar, informally::

  FrameSchema(name, module, magic, error,
              header="<struct fmt>", header_fields=(names...),
              count_fields=(header fields that are counts...),
              kinds=(Kind(name, value, cls?, marshal?, unmarshal?,
                          sections=(Section(name, elem, rname?)...)),),
              flags=(Flag(name, bit, section, scope)...),
              structs={module const: struct fmt},
              offsets={field: byte offset},     # SRG1 only
              bounds=(Bound(name, cap, scope)...),
              parse_scopes=(entry scopes...))

``error`` names the typed exception family every parse failure must
surface as (``FrameError`` for the frame formats, ``ProtoError`` for
the codec); anything else escaping a parse scope is a frame-totality
finding and a fuzzer crasher.
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass, field


class FrameError(Exception):
    """Typed parse failure for the frame formats (DGB2/DCB1/DRH1/
    SRG1).  Lives here — the root of the wire layer — so the schema's
    ``check_bound`` can raise it without importing a parser module;
    ``wire/distmsg.py`` re-exports it for the historical import
    path."""


@dataclass(frozen=True)
class Section:
    """One ordered body section of a frame kind.  ``elem`` is the
    element layout (i32 | i64 | u8 | u32 | f64 | blob | struct:NAME);
    ``rname`` is the unmarshal-side local name when it differs from
    the dataclass attribute (drift checking matches both sides)."""

    name: str
    elem: str
    rname: str = ""

    @property
    def read_name(self) -> str:
        return self.rname or self.name


@dataclass(frozen=True)
class Kind:
    """A frame kind: the wire constant, the dataclass that carries it
    (when one exists), its marshal/unmarshal scopes, and the ordered
    sections between header and trailing flag blocks."""

    name: str
    value: int
    cls: str = ""
    marshal: str = ""
    unmarshal: str = ""
    sections: tuple[Section, ...] = ()


@dataclass(frozen=True)
class Flag:
    """A header flag bit and the optional trailing section it gates.
    ``scope`` names the parse scope that must test the bit; "" means
    the bit is carried for a downstream consumer (reply-shape bits)
    and parse-side handling is not required."""

    name: str
    bit: int
    section: str = ""
    scope: str = ""


@dataclass(frozen=True)
class Bound:
    """Plausibility cap for one wire length/count field.  ``name`` is
    the dotted catalog key ("<format>.<field>"); ``scope`` the parse
    scope expected to enforce it ("" = anywhere in the module).  Caps
    are generous sanity limits — a 24-byte hostile frame must never
    drive a multi-GiB allocation — never tight operational limits."""

    name: str
    cap: int
    scope: str = ""
    doc: str = ""


@dataclass(frozen=True)
class ProtoField:
    """One gogoproto message field: number, attribute name, wire
    type, and whether the marshaler emits it conditionally."""

    fnum: int
    name: str
    wt: int
    optional: bool = False

    @property
    def tag(self) -> int:
        return (self.fnum << 3) | self.wt


@dataclass(frozen=True)
class ProtoMessage:
    cls: str
    fields: tuple[ProtoField, ...]


@dataclass(frozen=True)
class FrameSchema:
    name: str
    module: str
    magic: bytes | int
    error: str
    header: str = ""
    header_fields: tuple[str, ...] = ()
    header_size: int = 0
    count_fields: tuple[str, ...] = ()
    kinds: tuple[Kind, ...] = ()
    flags: tuple[Flag, ...] = ()
    structs: dict[str, str] = field(default_factory=dict)
    offsets: dict[str, int] = field(default_factory=dict)
    bounds: tuple[Bound, ...] = ()
    messages: tuple[ProtoMessage, ...] = ()
    parse_scopes: tuple[str, ...] = ()

    def header_struct(self) -> struct.Struct:
        return struct.Struct(self.header)

    def header_offsets(self) -> dict[str, tuple[int, int, bool]]:
        """{field: (byte offset, width, signed)} for the packed
        header — the fuzzer writes count-field extremes through
        this."""
        out: dict[str, tuple[int, int, bool]] = {}
        pos = 0
        toks = re.findall(r"(\d*)([a-zA-Z])", self.header)
        for name, (rep, ch) in zip(self.header_fields, toks):
            fmt = "<" + (rep + ch if ch == "s" else ch)
            width = struct.calcsize(fmt)
            out[name] = (pos, width, ch in "bhilq")
            pos += width
        return out

    def kind_values(self) -> dict[str, int]:
        return {k.name: k.value for k in self.kinds}


# ---------------------------------------------------------------------------
# the five formats
# ---------------------------------------------------------------------------

DGB2 = FrameSchema(
    name="DGB2",
    module="etcd_tpu/wire/distmsg.py",
    magic=b"DGB2",
    error="FrameError",
    header="<4sBBHIIII",
    header_fields=("magic", "kind", "sender", "flags",
                   "g", "e", "seq", "epoch"),
    count_fields=("g", "e"),
    kinds=(
        Kind("KIND_APPEND", 0, cls="AppendBatch",
             marshal="AppendBatch.marshal",
             unmarshal="AppendBatch.unmarshal",
             sections=(Section("term", "i32"),
                       Section("prev_idx", "i32"),
                       Section("prev_term", "i32"),
                       Section("n_ents", "i32"),
                       Section("commit", "i32"),
                       Section("ent_terms", "i32", rname="ets"),
                       Section("lens", "i32"),
                       Section("active", "u8"),
                       Section("need_snap", "u8"),
                       Section("payloads", "blob"))),
        Kind("KIND_APPEND_RESP", 1, cls="AppendResp",
             marshal="AppendResp.marshal",
             unmarshal="AppendResp.unmarshal",
             sections=(Section("term", "i32"),
                       Section("acked", "i32"),
                       Section("hint", "i32"),
                       Section("ok", "u8"),
                       Section("active", "u8"))),
        Kind("KIND_VOTE", 2, cls="VoteReq",
             marshal="VoteReq.marshal",
             unmarshal="VoteReq.unmarshal",
             sections=(Section("term", "i32"),
                       Section("last", "i32"),
                       Section("lterm", "i32"),
                       Section("active", "u8"))),
        Kind("KIND_VOTE_RESP", 3, cls="VoteResp",
             marshal="VoteResp.marshal",
             unmarshal="VoteResp.unmarshal",
             sections=(Section("term", "i32"),
                       Section("granted", "u8"),
                       Section("active", "u8"))),
        # declared for the client-propose lineage; never shipped on
        # the peer wire — unmarshal_any rejects it typed
        Kind("KIND_PROPOSE", 4),
    ),
    flags=(
        Flag("FLAG_TRACE", 0x0001, section="trace",
             scope="AppendBatch.unmarshal"),
        Flag("FLAG_PACKED", 0x0002, section="packed",
             scope="AppendBatch.unmarshal"),
    ),
    structs={"_HDR": "<4sBBHIIII", "_TRACE_ENT": "<iiIBxxx"},
    bounds=(
        Bound("dgb2.groups", 1 << 16, scope="parse_header",
              doc="co-hosted group lanes per frame"),
        Bound("dgb2.ents_per_lane", 1 << 16, scope="parse_header",
              doc="E axis of the [G, E] entry-term table"),
        Bound("dgb2.total_entries", 1 << 24,
              scope="AppendBatch.unmarshal",
              doc="sum(n_ents) payload blobs in one frame"),
        Bound("dgb2.payload_len", 1 << 26,
              scope="AppendBatch.unmarshal",
              doc="one entry payload blob"),
        Bound("dgb2.trace_count", 65536, scope="_read_trace",
              doc="head-sampled trace rows, never the batch"),
    ),
    parse_scopes=("parse_header", "_read_trace", "_read_packed",
                  "AppendBatch.unmarshal", "AppendResp.unmarshal",
                  "VoteReq.unmarshal", "VoteResp.unmarshal",
                  "unmarshal_any"),
)

DCB1 = FrameSchema(
    name="DCB1",
    module="etcd_tpu/wire/clientmsg.py",
    magic=b"DCB1",
    error="FrameError",
    header="<4sBBHI",
    header_fields=("magic", "kind", "flags", "reserved", "count"),
    count_fields=("count",),
    kinds=(
        Kind("KIND_GET_REQ", 0, unmarshal="unpack_get_request",
             sections=(Section("plens", "i32"),
                       Section("paths", "blob"))),
        Kind("KIND_GET_RESP", 1, unmarshal="unpack_get_response",
             sections=(Section("vlens", "i32"),
                       Section("errs", "struct:_ERR"),
                       Section("vals", "blob"),
                       Section("msgs", "blob"))),
        Kind("KIND_PROPOSE_RESP", 2,
             unmarshal="unpack_propose_response",
             sections=(Section("errs", "struct:_ERR"),
                       Section("msgs", "blob"))),
    ),
    structs={"_HDR": "<4sBBHI", "_ERR": "<iii"},
    bounds=(
        Bound("dcb1.count", 1 << 20, scope="_parse_header",
              doc="ops per client batch"),
        Bound("dcb1.path_len", 1 << 16, scope="unpack_get_request",
              doc="one utf-8 key path"),
        Bound("dcb1.val_len", 1 << 26, scope="unpack_get_response",
              doc="one value blob"),
        Bound("dcb1.msg_len", 1 << 16, scope="_unpack_errs",
              doc="one error message"),
    ),
    parse_scopes=("_parse_header", "unpack_get_request",
                  "_unpack_errs", "_slice_msgs",
                  "unpack_get_response", "unpack_propose_response"),
)

DRH1 = FrameSchema(
    name="DRH1",
    module="etcd_tpu/wire/rolemsg.py",
    magic=b"DRH1",
    error="FrameError",
    header="<4sBBHI",
    header_fields=("magic", "kind", "flags", "reserved", "count"),
    count_fields=("count",),
    kinds=(
        Kind("KIND_FWD_REQ", 0, unmarshal="unpack_fwd_request",
             sections=(Section("opflags", "u8"),
                       Section("rlens", "i32"),
                       Section("blobs", "blob"))),
        Kind("KIND_FWD_ACKS", 1, unmarshal="unpack_fwd_acks",
             sections=(Section("errs", "struct:_ERR"),
                       Section("msgs", "blob"))),
        Kind("KIND_FWD_VALS", 2, unmarshal="unpack_fwd_vals",
             sections=(Section("vlens", "i32"),
                       Section("errs", "struct:_ERR"),
                       Section("vals", "blob"),
                       Section("msgs", "blob"))),
        Kind("KIND_FWD_RESP", 3, unmarshal="unpack_fwd_response",
             sections=(Section("rows", "struct:_EVT"),
                       Section("blobs", "blob"))),
        Kind("KIND_COMMIT", 4, unmarshal="unpack_commit",
             sections=(Section("seq", "u64"),
                       Section("groups", "i32"),
                       Section("gindex", "i64"),
                       Section("rlens", "i32"),
                       Section("payloads", "blob"))),
    ),
    flags=(
        # reply-shape bits ride the header for the shard-side
        # dispatcher (server/roles.py); the parser hands them through
        Flag("REPLY_ACKS", 0x01),
        Flag("REPLY_VALS", 0x02),
    ),
    structs={"_HDR": "<4sBBHI", "_ERR": "<iii",
             "_EVT": "<iBBHqqqqqdiiii"},
    bounds=(
        Bound("drh1.count", 1 << 20, scope="_parse_header",
              doc="ops / rows per handoff frame"),
        Bound("drh1.blob_len", 1 << 26, scope="_slice_blobs",
              doc="one request/payload blob"),
        Bound("drh1.val_len", 1 << 26, scope="unpack_fwd_vals",
              doc="one value blob"),
        Bound("drh1.msg_len", 1 << 16, scope="_unpack_errs",
              doc="one error message"),
    ),
    parse_scopes=("_parse_header", "unpack_fwd_request",
                  "_unpack_errs", "_slice_msgs", "_slice_blobs",
                  "unpack_fwd_acks", "unpack_fwd_vals",
                  "unpack_fwd_response", "unpack_commit"),
)

SRG1 = FrameSchema(
    name="SRG1",
    module="etcd_tpu/server/shmring.py",
    magic=0x31475253,  # "SRG1" little-endian
    error="FrameError",
    # fixed-offset header, not a packed struct: cursors are single
    # aligned 8-byte stores and must not move if a field is added
    header_size=64,
    offsets={"magic": 0, "generation": 4, "head": 8, "tail": 16,
             "dropped": 24, "capacity": 32},
    bounds=(
        Bound("srg1.capacity", 1 << 30, scope="ShmRing._attach",
              doc="ring byte span, validated against segment size"),
        Bound("srg1.record_len", 1 << 26,
              doc="one length-prefixed record"),
    ),
    parse_scopes=("ShmRing._attach", "ShmRing._peek",
                  "ShmRing.pop"),
)

GPB1 = FrameSchema(
    name="GPB1",
    module="etcd_tpu/wire/proto.py",
    magic=b"",
    error="ProtoError",
    messages=(
        ProtoMessage("Entry", (
            ProtoField(1, "type", 0), ProtoField(2, "term", 0),
            ProtoField(3, "index", 0), ProtoField(4, "data", 2))),
        ProtoMessage("Snapshot", (
            ProtoField(1, "data", 2), ProtoField(2, "nodes", 0),
            ProtoField(3, "index", 0), ProtoField(4, "term", 0),
            ProtoField(5, "removed_nodes", 0))),
        ProtoMessage("Message", (
            ProtoField(1, "type", 0), ProtoField(2, "to", 0),
            ProtoField(3, "from_", 0), ProtoField(4, "term", 0),
            ProtoField(5, "log_term", 0), ProtoField(6, "index", 0),
            ProtoField(7, "entries", 2), ProtoField(8, "commit", 0),
            ProtoField(9, "snapshot", 2),
            ProtoField(10, "reject", 0))),
        ProtoMessage("HardState", (
            ProtoField(1, "term", 0), ProtoField(2, "vote", 0),
            ProtoField(3, "commit", 0))),
        ProtoMessage("ConfChange", (
            ProtoField(1, "id", 0), ProtoField(2, "type", 0),
            ProtoField(3, "node_id", 0),
            ProtoField(4, "context", 2))),
        ProtoMessage("Record", (
            ProtoField(1, "type", 0), ProtoField(2, "crc", 0),
            ProtoField(3, "data", 2, optional=True))),
        ProtoMessage("GroupEntry", (
            ProtoField(1, "kind", 0), ProtoField(2, "group", 0),
            ProtoField(3, "gindex", 0), ProtoField(4, "gterm", 0),
            ProtoField(5, "payload", 2, optional=True))),
        ProtoMessage("SnapPb", (
            ProtoField(1, "crc", 0),
            ProtoField(2, "data", 2, optional=True))),
    ),
    bounds=(
        Bound("gpb1.len", 1 << 30, scope="_bytes_field",
              doc="one length-delimited field"),
    ),
    parse_scopes=("uvarint", "_tag", "_skip_field", "_bytes_field",
                  "Entry.unmarshal", "Snapshot.unmarshal",
                  "Message.unmarshal", "HardState.unmarshal",
                  "ConfChange.unmarshal", "Record.unmarshal",
                  "GroupEntry.unmarshal", "SnapPb.unmarshal"),
)

FORMATS: tuple[FrameSchema, ...] = (DGB2, DCB1, DRH1, SRG1, GPB1)

#: schema by owning module relpath — the wire checkers key on this
MODULE_SCHEMAS: dict[str, FrameSchema] = {
    f.module: f for f in FORMATS}

#: closed catalog of every wire length/count plausibility cap.
#: ``check_bound`` call sites must name a key from this dict with a
#: string literal — the wire-bounds checker rejects dynamic names and
#: unknown keys (the fault-vocabulary pattern, PR 10).
BOUNDS: dict[str, int] = {
    b.name: b.cap for f in FORMATS for b in f.bounds}

#: function/method names the wire checkers treat as parse scopes in
#: ANY wire-target file (fixture trees included) — the schema
#: parse_scopes pin the real modules' entry points exactly
PARSE_NAME_RE = re.compile(
    r"^(unmarshal|unpack_|parse_|_parse_|_read_|_unpack_|_slice_"
    r"|uvarint$|_tag$|_skip_field$|_bytes_field$|_peek$|pop$)")


def check_bound(name: str, value: int,
                err: type[Exception] = FrameError) -> None:
    """Reject a wire-derived length/count outside its declared
    plausibility cap — typed, before it can size an allocation or a
    loop.  ``name`` must be a string literal from ``BOUNDS`` (lint
    enforces the closed vocabulary)."""
    if value < 0 or value > BOUNDS[name]:
        raise err(f"implausible {name} {value} "
                  f"(cap {BOUNDS[name]})")
