"""Length-prefixed binary framing for the batch client endpoints
(PR 14).

``get_many``/``propose_many`` JSON-marshal every op on the hot path:
a 100-op read batch costs ~100 f-string path encodes + one
``json.dumps`` on the client and a ``json.loads`` + per-op dict hops
on the server, and the reply pays the same again.  This module is
the binary alternative — fixed-width tables and value blobs
assembled in a handful of C-level join/encode calls (never a
per-op Python loop on the hot shape) and unmarshaled as
``np.frombuffer`` views + single-pass decodes, the client-wire
analog of ``wire/distmsg.py``'s peer frames.

Negotiation is via Content-Type/Accept (server/distserver.py
``_make_peer_handler``): HTTP+JSON stays the default and is
byte-for-byte unchanged; a binary-capable client advertises
``Accept: application/x-etcd-batch`` and only switches its request
bodies over after the server has answered in kind, so a mixed-
version pair degrades to JSON with zero failed ops.

Frame = 12-byte header + kind-specific sections:

  header:   magic "DCB1" | kind u8 | flags u8 | reserved u16 |
            count u32
  GET_REQ:  plens  [count] i32  + concatenated utf-8 paths
  GET_RESP: vlens  [count] i32  (-1 = key absent / errored)
            + n_errs u32 + (idx i32, code i32, mlen i32) * n_errs
            + concatenated value bytes + concatenated utf-8 messages
  PROPOSE_RESP:
            n_errs u32 + (idx i32, code i32, mlen i32) * n_errs
            + concatenated utf-8 messages

Error tables are SPARSE (idx names the failed op) — the common
all-ok reply of a 1000-op propose batch is 16 bytes.  Decoder
totality matches the peer tier: every malformed frame fails typed as
``FrameError``, never an untyped crash (mutation fuzz in
tests/test_wire_client.py).
"""

from __future__ import annotations

import struct

import numpy as np

from .distmsg import FrameError, _view_i32
from .schema import DCB1, check_bound

#: negotiated media type; requests carry it as Accept (capability
#: advert) and, once confirmed, as Content-Type on binary bodies
CONTENT_TYPE = "application/x-etcd-batch"

# layout constants come from the declarative schema (wire/schema.py)
_MAGIC = DCB1.magic
_HDR = DCB1.header_struct()

_KINDS = DCB1.kind_values()
KIND_GET_REQ = _KINDS["KIND_GET_REQ"]
KIND_GET_RESP = _KINDS["KIND_GET_RESP"]
KIND_PROPOSE_RESP = _KINDS["KIND_PROPOSE_RESP"]

#: one sparse error row: op index i32, error code i32, msg len i32
_ERR = struct.Struct(DCB1.structs["_ERR"])


def _parse_header(data) -> tuple[int, int]:
    """Returns (kind, count); raises FrameError."""
    if len(data) < _HDR.size:
        raise FrameError("short client frame")
    magic, kind, _flags, _rsvd, count = _HDR.unpack_from(data)
    if magic != _MAGIC:
        raise FrameError("bad client frame magic")
    # the header count sizes every downstream table view and the
    # propose-ack return value — cap it before anything allocates
    # (it used to flow through unpack_propose_response unchecked)
    check_bound("dcb1.count", count)
    return kind, count


def pack_get_request(paths: list[str]) -> bytes:
    """One C-level join + encode for the whole batch: utf-8 of a
    concatenation is the concatenation of the utf-8, so the blob
    never needs per-path encodes — only the LENGTH table does, and
    only when a path is non-ASCII (char count != byte count)."""
    joined = "".join(paths)
    blob = joined.encode()
    if len(blob) == len(joined):
        lens = np.fromiter(map(len, paths), "<i4",
                           count=len(paths))
    else:
        lens = np.fromiter((len(p.encode()) for p in paths),
                           "<i4", count=len(paths))
    return b"".join((
        _HDR.pack(_MAGIC, KIND_GET_REQ, 0, 0, len(paths)),
        lens.tobytes(), blob))


def unpack_get_request(data) -> list[str]:
    kind, count = _parse_header(data)
    if kind != KIND_GET_REQ:
        raise FrameError(f"kind {kind} != get_req")
    plens, pos = _view_i32(data, _HDR.size, count)
    if count == 0:
        return []
    if int(plens.min()) < 0:
        raise FrameError("negative path length")
    check_bound("dcb1.path_len", int(plens.max()))
    # int64 running ends: an adversarial table of huge i32 lens must
    # overflow into the bounds check, not wrap into a wrong slice
    ends = plens.cumsum(dtype=np.int64)
    total = int(ends[-1])
    if pos + total > len(data):
        raise FrameError("truncated path")
    blob = data[pos:pos + total]
    if not isinstance(blob, (bytes, bytearray)):
        blob = bytes(blob)
    try:
        s = blob.decode()
    except UnicodeDecodeError:
        raise FrameError("path not utf-8") from None
    if len(s) == total:
        # ASCII blob: char offsets == byte offsets, so the paths
        # are plain slices of the ONE decoded string (the hot shape
        # — this is what keeps the batch parse off the stage table)
        out = []
        a = 0
        for b in ends.tolist():
            out.append(s[a:b])
            a = b
        return out
    out = []
    a = 0
    for b in ends.tolist():
        try:
            out.append(blob[a:b].decode())
        except UnicodeDecodeError:
            # the whole blob decoded, so a per-path failure means
            # the length table splits a multibyte character
            raise FrameError("path not utf-8") from None
        a = b
    return out


def _pack_errs(errs) -> tuple[bytes, list[bytes]]:
    """Errs table bytes + the message blobs to append after values.
    ``errs``: {op_index: (code, message)} sparse map."""
    lead = bytearray(4 + _ERR.size * len(errs))
    struct.pack_into("<I", lead, 0, len(errs))
    pos = 4
    msgs = []
    for idx in sorted(errs):
        code, msg = errs[idx]
        mb = msg.encode()
        _ERR.pack_into(lead, pos, idx, code, len(mb))
        pos += _ERR.size
        msgs.append(mb)
    return bytes(lead), msgs


def _unpack_errs(data, pos: int,
                 count: int) -> tuple[list[tuple[int, int, int]],
                                      int]:
    """Parse the sparse errs table; returns ([(idx, code, mlen)],
    pos past the table).  Message bytes trail the frame's other
    blobs and are sliced by the caller."""
    if pos + 4 > len(data):
        raise FrameError("truncated errs table")
    (n_errs,) = struct.unpack_from("<I", data, pos)
    pos += 4
    if n_errs > count:
        raise FrameError(f"errs {n_errs} > ops {count}")
    end = pos + n_errs * _ERR.size
    if end > len(data):
        raise FrameError("truncated errs table")
    rows = []
    for _ in range(n_errs):
        idx, code, mlen = _ERR.unpack_from(data, pos)
        pos += _ERR.size
        if idx < 0 or idx >= count:
            raise FrameError("errs index out of range")
        check_bound("dcb1.msg_len", mlen)
        rows.append((idx, code, mlen))
    return rows, pos


def _slice_msgs(data, pos: int,
                rows) -> dict[int, tuple[int, str]]:
    errs: dict[int, tuple[int, str]] = {}
    buf = memoryview(data)
    for idx, code, mlen in rows:
        if pos + mlen > len(data):
            raise FrameError("truncated errs message")
        try:
            errs[idx] = (code, str(buf[pos:pos + mlen], "utf-8"))
        except UnicodeDecodeError:
            raise FrameError("errs message not utf-8") from None
        pos += mlen
    return errs


#: values are encoded in chunks of this many ops so every
#: intermediate join/encode buffer stays pooled-arena/cache sized;
#: only the OUTPUT is ever allocated at full frame size, and it is
#: written exactly once (a whole-blob join+encode+join costs three
#: full-size memory passes — that triple showed up as the marshal
#: stage's cost at KB values, not the per-op Python work)
_VAL_CHUNK = 32


def pack_get_response(vals, errs: dict[int, tuple[int, str]]
                      ) -> bytearray | bytes:
    """``vals``: value per op — str (the serving path hands store
    leaf values straight through), bytes, or None (absent/errored).
    The all-present all-str batch — the hot serve shape — encodes
    chunk-wise straight into the preallocated frame; None/bytes
    (chunk join raises TypeError) or non-ASCII text (byte length
    outruns the char-count table) fall back to the per-value
    path."""
    lead, msgs = _pack_errs(errs)
    count = len(vals)
    mblob = b"".join(msgs)
    try:
        lens = np.fromiter(map(len, vals), "<i4", count=count)
        total = int(lens.sum(dtype=np.int64))
        head = _HDR.size + 4 * count + len(lead)
        out = bytearray(head + total + len(mblob))
        _HDR.pack_into(out, 0, _MAGIC, KIND_GET_RESP, 0, 0, count)
        out[_HDR.size:_HDR.size + 4 * count] = lens.tobytes()
        out[_HDR.size + 4 * count:head] = lead
        a = head
        for i in range(0, count, _VAL_CHUNK):
            b = "".join(vals[i:i + _VAL_CHUNK]).encode()
            e = a + len(b)
            out[a:e] = b
            a = e
        if a == head + total:
            out[a:] = mblob
            return out
        # non-ASCII: utf-8 byte lens exceed the char-count table we
        # optimistically wrote — rebuild on the general path
    except TypeError:
        pass  # a None (len) or bytes (str join) value in the batch
    lens = []
    parts = []
    for v in vals:
        if v is None:
            lens.append(-1)
            continue
        if type(v) is bytes:
            b = v
        else:
            b = str(v).encode()
        parts.append(b)
        lens.append(len(b))
    blob = b"".join(parts)
    return b"".join((
        _HDR.pack(_MAGIC, KIND_GET_RESP, 0, 0, count),
        np.asarray(lens, "<i4").tobytes(), lead, blob, mblob))


def unpack_get_response(
        data) -> tuple[list[bytes | None],
                       dict[int, tuple[int, str]]]:
    kind, count = _parse_header(data)
    if kind != KIND_GET_RESP:
        raise FrameError(f"kind {kind} != get_resp")
    vlens, pos = _view_i32(data, _HDR.size, count)
    if count and int(vlens.min()) < -1:
        raise FrameError("bad value length")
    if count:
        # -1 rows mean "absent" and are legal — cap the largest
        # actual value length only
        check_bound("dcb1.val_len", max(0, int(vlens.max())))
    rows, pos = _unpack_errs(data, pos, count)
    total = int(np.maximum(vlens, 0).sum(dtype=np.int64))
    if pos + total > len(data):
        raise FrameError("truncated value blob")
    vals: list[bytes | None] = []
    a = pos
    for ln in vlens.tolist():
        if ln < 0:
            vals.append(None)
        else:
            b = a + ln
            vals.append(bytes(data[a:b]))
            a = b
    return vals, _slice_msgs(data, a, rows)


def pack_propose_response(
        count: int, errs: dict[int, tuple[int, str]]) -> bytearray:
    lead, msgs = _pack_errs(errs)
    blob_total = sum(len(b) for b in msgs)
    out = bytearray(_HDR.size + len(lead) + blob_total)
    _HDR.pack_into(out, 0, _MAGIC, KIND_PROPOSE_RESP, 0, 0, count)
    pos = _HDR.size
    out[pos:pos + len(lead)] = lead
    pos += len(lead)
    for b in msgs:
        out[pos:pos + len(b)] = b
        pos += len(b)
    return out


def unpack_propose_response(
        data) -> tuple[int, dict[int, tuple[int, str]]]:
    kind, count = _parse_header(data)
    if kind != KIND_PROPOSE_RESP:
        raise FrameError(f"kind {kind} != propose_resp")
    rows, pos = _unpack_errs(data, _HDR.size, count)
    return count, _slice_msgs(data, pos, rows)
