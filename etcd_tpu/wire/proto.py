"""Hand-rolled protobuf wire codec for the etcd record types.

The reference uses gogoproto-generated marshalers with fixed field
emission order and `nullable=false` semantics (every required/non-null
field is written even when zero).  We reproduce that layout exactly so
files interoperate byte-for-byte:

- ``Entry``      reference raft/raftpb/raft.proto:16-21, raft.pb.go:921-943
- ``Snapshot``   raft.proto:23-29, raft.pb.go:954-999
- ``Message``    raft.proto:31-42, raft.pb.go:1010-1068
- ``HardState``  raft.proto:44-48, raft.pb.go:1079-1097
- ``ConfChange`` raft.proto:55-60, raft.pb.go:1108-1134
- ``Record``     wal/walpb/record.proto:10-14, record.pb.go:175-196
- ``SnapPb``     snap/snappb/snap.proto, snap.pb.go:158-175

Unmarshaling is a permissive field-number dispatch (standard proto
semantics: any order, unknown fields skipped), matching the generated
Unmarshal functions' behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .schema import check_bound

_MASK64 = (1 << 64) - 1


class ProtoError(ValueError):
    pass


class CRCMismatchError(ProtoError):
    """Record CRC mismatch.  Lives at the wire layer like the
    reference's walpb.ErrCRCMismatch (wal/walpb/record.go:20)."""


# ---------------------------------------------------------------------------
# varint primitives
# ---------------------------------------------------------------------------

def put_uvarint(buf: bytearray, v: int) -> None:
    v &= _MASK64
    while v >= 0x80:
        buf.append((v & 0x7F) | 0x80)
        v >>= 7
    buf.append(v)


def _tag(data: bytes, pos: int) -> tuple[int, int, int]:
    """Read a field tag, rejecting field number 0 — the generated
    unmarshalers error with "illegal tag 0" (gogoproto) rather than
    skipping; parity matters because a zero tag usually means a
    corrupt or misframed buffer."""
    tag, pos = uvarint(data, pos)
    fnum, wt = tag >> 3, tag & 7
    if fnum == 0:
        raise ProtoError(f"illegal tag 0 (wire type {wt})")
    return fnum, wt, pos


def uvarint(data: bytes, pos: int) -> tuple[int, int]:
    """Decode a varint at ``pos``; returns (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ProtoError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & _MASK64, pos
        shift += 7
        if shift >= 70:
            raise ProtoError("varint overflow")


def _skip_field(data: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:  # varint
        _, pos = uvarint(data, pos)
        return pos
    elif wire_type == 1:  # fixed64
        pos += 8
    elif wire_type == 2:  # length-delimited
        n, pos = uvarint(data, pos)
        pos += n
    elif wire_type == 5:  # fixed32
        pos += 4
    else:
        raise ProtoError(f"unsupported wire type {wire_type}")
    if pos > len(data):
        raise ProtoError("truncated field")
    return pos


def _expect_wt(fnum: int, wt: int, want: int) -> None:
    """Known fields must carry their declared wire type — the generated
    unmarshalers error with 'wrong wireType' rather than skipping
    (e.g. raft.pb.go Entry.Unmarshal), and replay parity depends on
    corrupt framing aborting instead of being masked."""
    if wt != want:
        raise ProtoError(f"field {fnum}: wrong wire type {wt}, want {want}")


def _bytes_field(data: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = uvarint(data, pos)
    check_bound("gpb1.len", n, err=ProtoError)
    if pos + n > len(data):
        raise ProtoError("truncated bytes field")
    return bytes(data[pos : pos + n]), pos + n


def _tagged_varint(buf: bytearray, tag: int, v: int) -> None:
    buf.append(tag)
    put_uvarint(buf, v)


def _tagged_bytes(buf: bytearray, tag: int, b: bytes) -> None:
    buf.append(tag)
    put_uvarint(buf, len(b))
    buf.extend(b)


# ---------------------------------------------------------------------------
# enums / message type constants (reference raft/raft.go:17-27)
# ---------------------------------------------------------------------------

ENTRY_NORMAL = 0
ENTRY_CONF_CHANGE = 1

CONF_CHANGE_ADD_NODE = 0
CONF_CHANGE_REMOVE_NODE = 1

MSG_HUP = 0
MSG_BEAT = 1
MSG_PROP = 2
MSG_APP = 3
MSG_APP_RESP = 4
MSG_VOTE = 5
MSG_VOTE_RESP = 6
MSG_SNAP = 7
MSG_DENIED = 8

MSG_NAMES = (
    "msgHup",
    "msgBeat",
    "msgProp",
    "msgApp",
    "msgAppResp",
    "msgVote",
    "msgVoteResp",
    "msgSnap",
    "msgDenied",
)


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

@dataclass(slots=True)
class Entry:
    type: int = ENTRY_NORMAL
    term: int = 0
    index: int = 0
    data: bytes = b""

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.type)
        _tagged_varint(buf, 0x10, self.term)
        _tagged_varint(buf, 0x18, self.index)
        _tagged_bytes(buf, 0x22, self.data)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Entry":
        e = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                e.type, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                e.term, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                e.index, pos = uvarint(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 2)
                e.data, pos = _bytes_field(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return e


@dataclass(slots=True)
class Snapshot:
    data: bytes = b""
    nodes: list[int] = field(default_factory=list)
    index: int = 0
    term: int = 0
    removed_nodes: list[int] = field(default_factory=list)

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_bytes(buf, 0x0A, self.data)
        for n in self.nodes:
            _tagged_varint(buf, 0x10, n)
        _tagged_varint(buf, 0x18, self.index)
        _tagged_varint(buf, 0x20, self.term)
        for n in self.removed_nodes:
            _tagged_varint(buf, 0x28, n)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Snapshot":
        s = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 2)
                s.data, pos = _bytes_field(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                s.nodes.append(v)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                s.index, pos = uvarint(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 0)
                s.term, pos = uvarint(data, pos)
            elif fnum == 5:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                s.removed_nodes.append(v)
            else:
                pos = _skip_field(data, pos, wt)
        return s

    def clone(self) -> "Snapshot":
        return Snapshot(self.data, list(self.nodes), self.index, self.term,
                        list(self.removed_nodes))


@dataclass(slots=True)
class Message:
    type: int = 0
    to: int = 0
    from_: int = 0
    term: int = 0
    log_term: int = 0
    index: int = 0
    entries: list[Entry] = field(default_factory=list)
    commit: int = 0
    snapshot: Snapshot = field(default_factory=Snapshot)
    reject: bool = False

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.type)
        _tagged_varint(buf, 0x10, self.to)
        _tagged_varint(buf, 0x18, self.from_)
        _tagged_varint(buf, 0x20, self.term)
        _tagged_varint(buf, 0x28, self.log_term)
        _tagged_varint(buf, 0x30, self.index)
        for e in self.entries:
            _tagged_bytes(buf, 0x3A, e.marshal())
        _tagged_varint(buf, 0x40, self.commit)
        _tagged_bytes(buf, 0x4A, self.snapshot.marshal())
        _tagged_varint(buf, 0x50, 1 if self.reject else 0)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Message":
        m = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                m.type, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                m.to, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                m.from_, pos = uvarint(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 0)
                m.term, pos = uvarint(data, pos)
            elif fnum == 5:
                _expect_wt(fnum, wt, 0)
                m.log_term, pos = uvarint(data, pos)
            elif fnum == 6:
                _expect_wt(fnum, wt, 0)
                m.index, pos = uvarint(data, pos)
            elif fnum == 7:
                _expect_wt(fnum, wt, 2)
                b, pos = _bytes_field(data, pos)
                m.entries.append(Entry.unmarshal(b))
            elif fnum == 8:
                _expect_wt(fnum, wt, 0)
                m.commit, pos = uvarint(data, pos)
            elif fnum == 9:
                _expect_wt(fnum, wt, 2)
                b, pos = _bytes_field(data, pos)
                m.snapshot = Snapshot.unmarshal(b)
            elif fnum == 10:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                m.reject = bool(v)
            else:
                pos = _skip_field(data, pos, wt)
        return m


@dataclass(slots=True)
class HardState:
    term: int = 0
    vote: int = 0
    commit: int = 0

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.term)
        _tagged_varint(buf, 0x10, self.vote)
        _tagged_varint(buf, 0x18, self.commit)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "HardState":
        s = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                s.term, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                s.vote, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                s.commit, pos = uvarint(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return s


EMPTY_HARD_STATE = HardState()


def is_empty_hard_state(st: HardState) -> bool:
    """Reference raft/node.go:69-76."""
    return st.term == 0 and st.vote == 0 and st.commit == 0


def is_empty_snap(sp: Snapshot) -> bool:
    """Reference raft/node.go:79-81."""
    return sp.index == 0


@dataclass(slots=True)
class ConfChange:
    id: int = 0
    type: int = CONF_CHANGE_ADD_NODE
    node_id: int = 0
    context: bytes = b""

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.id)
        _tagged_varint(buf, 0x10, self.type)
        _tagged_varint(buf, 0x18, self.node_id)
        _tagged_bytes(buf, 0x22, self.context)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "ConfChange":
        c = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                c.id, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                c.type, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                c.node_id, pos = uvarint(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 2)
                c.context, pos = _bytes_field(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return c


@dataclass(slots=True)
class Record:
    """WAL record (reference wal/walpb/record.proto:10-14).

    ``data=None`` omits field 3 entirely, mirroring the generated
    marshaler's nil check (record.pb.go:186).
    """

    type: int = 0
    crc: int = 0
    data: bytes | None = None

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.type)
        _tagged_varint(buf, 0x10, self.crc)
        if self.data is not None:
            _tagged_bytes(buf, 0x1A, self.data)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Record":
        r = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                r.type, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                r.crc, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 2)
                r.data, pos = _bytes_field(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return r

    def validate(self, crc: int) -> None:
        """Reference wal/walpb/record.go:25 — raise on CRC mismatch."""
        if self.crc != crc:
            raise CRCMismatchError(
                f"crc mismatch: record={self.crc:#x} computed={crc:#x}")


@dataclass(slots=True)
class GroupEntry:
    """Multi-group WAL envelope (new work — no reference counterpart:
    the reference runs ONE raft group per process, so its WAL needs no
    group axis; the co-hosted server multiplexes G groups into one
    record stream, keeping file count O(1) and the whole log
    replayable as a single device batch).

    ``kind``: 0 = a group's log entry (payload = marshaled Request),
    1 = commit-frontier marker (payload = the [G] i32-LE commit vector
    followed by the [G] i32-LE term-at-commit vector).
    """

    kind: int = 0
    group: int = 0
    gindex: int = 0
    gterm: int = 0
    payload: bytes | None = None

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.kind)
        _tagged_varint(buf, 0x10, self.group)
        _tagged_varint(buf, 0x18, self.gindex)
        _tagged_varint(buf, 0x20, self.gterm)
        if self.payload is not None:
            _tagged_bytes(buf, 0x2A, self.payload)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "GroupEntry":
        ge = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                ge.kind, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 0)
                ge.group, pos = uvarint(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 0)
                ge.gindex, pos = uvarint(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 0)
                ge.gterm, pos = uvarint(data, pos)
            elif fnum == 5:
                _expect_wt(fnum, wt, 2)
                ge.payload, pos = _bytes_field(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return ge


def marshal_group_entries(kind: int, groups, gindexes, gterms,
                          payloads) -> list[bytes]:
    """Batch-marshal GroupEntry envelopes without constructing the
    dataclass per record (the serving loop's WAL record builder runs
    this for every entry of every group in a frame — PR 14 hoists
    the per-record object churn out of that hot loop).  Byte-
    identical to ``GroupEntry(...).marshal()`` element-wise: all four
    varint fields are always written and a payload is written iff it
    is not None (``b""`` included)."""
    out = []
    for g, gi, gt, p in zip(groups, gindexes, gterms, payloads):
        buf = bytearray()
        _tagged_varint(buf, 0x08, kind)
        _tagged_varint(buf, 0x10, g)
        _tagged_varint(buf, 0x18, gi)
        _tagged_varint(buf, 0x20, gt)
        if p is not None:
            _tagged_bytes(buf, 0x2A, p)
        out.append(bytes(buf))
    return out


@dataclass(slots=True)
class SnapPb:
    """Snapshot file wrapper (reference snap/snappb/snap.proto).

    ``data=None`` omits field 2, mirroring snap.pb.go:165.
    """

    crc: int = 0
    data: bytes | None = None

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.crc)
        if self.data is not None:
            _tagged_bytes(buf, 0x12, self.data)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "SnapPb":
        s = cls()
        pos = 0
        while pos < len(data):
            fnum, wt, pos = _tag(data, pos)
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                s.crc, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 2)
                s.data, pos = _bytes_field(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return s
