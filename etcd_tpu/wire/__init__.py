"""L2 wire formats: gogoproto-compatible protobuf codecs.

Byte-compatible with the reference's generated marshalers
(raft/raftpb/raft.pb.go, wal/walpb/record.pb.go, snap/snappb/snap.pb.go)
so that WAL segments and snapshot files written by either implementation
replay in the other.
"""

from .proto import (
    Entry,
    Snapshot,
    Message,
    HardState,
    ConfChange,
    GroupEntry,
    Record,
    SnapPb,
    ENTRY_NORMAL,
    ENTRY_CONF_CHANGE,
    CONF_CHANGE_ADD_NODE,
    CONF_CHANGE_REMOVE_NODE,
    MSG_HUP,
    MSG_BEAT,
    MSG_PROP,
    MSG_APP,
    MSG_APP_RESP,
    MSG_VOTE,
    MSG_VOTE_RESP,
    MSG_SNAP,
    MSG_DENIED,
    EMPTY_HARD_STATE,
    is_empty_hard_state,
    is_empty_snap,
)

__all__ = [
    "Entry",
    "Snapshot",
    "Message",
    "HardState",
    "ConfChange",
    "GroupEntry",
    "Record",
    "SnapPb",
    "ENTRY_NORMAL",
    "ENTRY_CONF_CHANGE",
    "CONF_CHANGE_ADD_NODE",
    "CONF_CHANGE_REMOVE_NODE",
    "MSG_HUP",
    "MSG_BEAT",
    "MSG_PROP",
    "MSG_APP",
    "MSG_APP_RESP",
    "MSG_VOTE",
    "MSG_VOTE_RESP",
    "MSG_SNAP",
    "MSG_DENIED",
    "EMPTY_HARD_STATE",
    "is_empty_hard_state",
    "is_empty_snap",
]
