"""Batched inter-host consensus frames for the distributed
multi-group server (SURVEY §5.8's DCN tier).

The reference's peer transport ships ONE raftpb.Message per HTTP POST
(etcdserver/cluster_store.go:106-156).  The distributed multi-group
server hosts one member slot of ALL G co-hosted groups per process,
so a replication round produces G messages *per peer* — shipped here
as ONE binary frame of [G] arrays (the batched analog: same
fire-and-forget, drop-tolerant contract, server.go:202-206, but the
unit of transport is the whole group batch).

Frame = 24-byte header + fixed [G] sections + payload table:

  header:  magic "DGB2" | kind u8 | sender_slot u8 | flags u16 |
           g u32 | e u32 | seq u32 | epoch u32
  body:    kind-specific little-endian arrays (see each class);
           i32 sections lead so every array lands 4-aligned, u8
           masks trail
  payload: lens [sum(n_ents)] i32 + concatenated blobs (appends only)

``seq``/``epoch`` are the PIPELINE tags (PR 5): the leader numbers
every append frame per peer (seq) within a leadership epoch (bumped
whenever the local leadership set changes), and the follower echoes
both into its response — acks may then return OUT OF ORDER over
striped connections and still be matched to the exact in-flight
frame, with duplicate and stale-epoch responses rejected instead of
corrupting progress state.  Vote frames carry zeros (the campaign
round-trip stays lockstep).

Arrays are raw numpy little-endian — the receiving end feeds them
straight into the batched engine (raft/batched.py) without a decode
loop: wire layout == device layout is the point.

Copy discipline: ``marshal`` writes every section straight into ONE
preallocated bytearray (no intermediate ``tobytes``/join garbage —
at depth-8 pipelining the old form allocated ~10 temporaries per
frame per peer), and ``unmarshal`` returns ``np.frombuffer`` views
over the received buffer (read-only; the engine copies on device
put).  Payload blobs are the one deliberate copy on unpack: they
outlive the frame buffer in the host payload ring, and a memoryview
would pin the whole frame.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

from .schema import DGB2, FrameError, check_bound

__all__ = [
    "FrameError", "AppendBatch", "AppendResp", "VoteReq", "VoteResp",
    "PackedPayloads", "parse_header", "unmarshal_any",
    "flat_entry_table", "KIND_APPEND", "KIND_APPEND_RESP",
    "KIND_VOTE", "KIND_VOTE_RESP", "KIND_PROPOSE", "FLAG_TRACE",
    "FLAG_PACKED",
]

# layout constants come from the declarative schema (wire/schema.py)
# — the schema-drift checker fails lint on a locally re-declared
# struct/magic literal in this module
_MAGIC = DGB2.magic
_HDR = DGB2.header_struct()

_KINDS = DGB2.kind_values()
KIND_APPEND = _KINDS["KIND_APPEND"]
KIND_APPEND_RESP = _KINDS["KIND_APPEND_RESP"]
KIND_VOTE = _KINDS["KIND_VOTE"]
KIND_VOTE_RESP = _KINDS["KIND_VOTE_RESP"]
KIND_PROPOSE = _KINDS["KIND_PROPOSE"]

# Header flag bits.  FLAG_TRACE (PR 8): the frame carries an
# OPTIONAL trace block AFTER the payload table — (group, gindex,
# trace_id, origin) per head-sampled entry, the distributed-trace
# context followers stamp their span events with.  Versioning is
# structural: an old peer ignores unknown flag bits and never reads
# past the sections it knows (trailing bytes are ignored), so a
# traced frame parses on old peers exactly as an untraced one; an
# untraced frame (flags=0) is BYTE-IDENTICAL to the pre-trace
# layout, so new peers interop with old senders for free.
_FLAG_BITS = {f.name: f.bit for f in DGB2.flags}
FLAG_TRACE = _FLAG_BITS["FLAG_TRACE"]

# FLAG_PACKED (PR 14): the frame carries an OPTIONAL flat entry
# table AFTER the payload blobs (and after the trace block when both
# are present — trailing sections appear in flag-bit order):
#
#   u32 total | ent_group [total] i32 | ent_gindex [total] i32
#
# One row per carried entry, in frame order: the group lane and the
# absolute group index (prev_idx[g]+1+j) of each payload blob.  The
# receiver's serving loop consumes entries FLAT — one pass over the
# table builds every WAL record and stores every payload without a
# per-group dict hop.  The table is redundant with (prev_idx,
# n_ents), which is exactly why it is validated on unmarshal (count,
# range, per-lane histogram): a corrupted table cannot disagree with
# the [G] sections without failing typed as FrameError.  Same
# structural versioning as FLAG_TRACE: old peers ignore the bit and
# the trailing bytes; an unpacked frame is byte-identical to DGB2.
FLAG_PACKED = _FLAG_BITS["FLAG_PACKED"]

#: one trace entry: group i32, gindex i32, trace_id u32, origin u8
#: (+3 pad — keeps entries 16-byte and the block 4-aligned)
_TRACE_ENT = struct.Struct(DGB2.structs["_TRACE_ENT"])


def _view_i32(data, pos: int, n: int) -> tuple[np.ndarray, int]:
    """Read-only [n] i32 view over the frame buffer (no copy)."""
    end = pos + 4 * n
    if end > len(data):
        raise FrameError("truncated i32 section")
    return np.frombuffer(data, "<i4", count=n, offset=pos), end


def _view_u8(data, pos: int, n: int) -> tuple[np.ndarray, int]:
    end = pos + n
    if end > len(data):
        raise FrameError("truncated u8 section")
    return np.frombuffer(data, np.uint8, count=n, offset=pos), end


def _w_i32(buf: bytearray, pos: int, arr) -> int:
    """Write ``arr`` as little-endian i32 straight into ``buf`` at
    ``pos`` (the preallocated-frame write path: one cast-assign into
    a frombuffer view, no intermediate bytes object)."""
    a = np.asarray(arr)
    n = a.size
    if n:
        np.frombuffer(buf, "<i4", count=n, offset=pos)[:] = a.ravel()
    return pos + 4 * n


def _w_u8(buf: bytearray, pos: int, arr) -> int:
    a = np.asarray(arr)
    n = a.size
    if n:
        np.frombuffer(buf, np.uint8, count=n,
                      offset=pos)[:] = a.ravel()
    return pos + n


def parse_header(data) -> tuple[int, int, int, int, int, int, int]:
    """Returns (kind, sender_slot, g, e, seq, epoch, flags); raises
    FrameError."""
    if len(data) < _HDR.size:
        raise FrameError("short frame")
    magic, kind, sender, flags, g, e, seq, epoch = \
        _HDR.unpack_from(data)
    if magic != _MAGIC:
        raise FrameError("bad magic")
    check_bound("dgb2.groups", g)
    check_bound("dgb2.ents_per_lane", e)
    return kind, sender, g, e, seq, epoch, flags


def _read_trace(
        data, pos: int) -> tuple[list[tuple[int, int, int, int]], int]:
    """Parse the optional trailing trace block at ``pos`` (the
    FLAG_TRACE bit was set).  Raises FrameError on truncation or an
    implausible count — a flipped flag bit must fail typed, never
    escape as IndexError/struct.error."""
    if pos + 4 > len(data):
        raise FrameError("truncated trace block")
    (n,) = struct.unpack_from("<I", data, pos)
    pos += 4
    check_bound("dgb2.trace_count", n)
    end = pos + n * _TRACE_ENT.size
    if end > len(data):
        raise FrameError("truncated trace block")
    out = []
    for _ in range(n):
        g, gi, tid, org = _TRACE_ENT.unpack_from(data, pos)
        out.append((g, gi, tid, org))
        pos += _TRACE_ENT.size
    return out, pos


def _read_packed(data, pos: int, prev_idx, n_ents,
                 total: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Parse + validate the trailing FLAG_PACKED entry table.  The
    table is fully determined by (prev_idx, n_ents) — row k of lane
    g MUST be (g, prev_idx[g]+1+j) in frame order — so it is checked
    for exact equality against the recomputed layout: a mutated
    table fails typed here instead of mis-routing entries in the
    receiver's flat store loop, and downstream consumers may index
    ent_terms[group, gindex-prev_idx-1] without re-validating."""
    if pos + 4 > len(data):
        raise FrameError("truncated packed table")
    (n,) = struct.unpack_from("<I", data, pos)
    pos += 4
    if n != total:
        raise FrameError(
            f"packed table count {n} != sum(n_ents) {total}")
    groups, pos = _view_i32(data, pos, n)
    gindex, pos = _view_i32(data, pos, n)
    exp_g, exp_i = flat_entry_table(prev_idx, n_ents)
    if not (np.array_equal(groups, exp_g)
            and np.array_equal(gindex, exp_i)):
        raise FrameError("packed table disagrees with [G] sections")
    return groups, gindex, pos


class PackedPayloads:
    """Flat payload storage for an AppendBatch: one ``list[bytes]``
    in frame order plus a [G+1] starts table (cumsum of n_ents).
    Indexing by group returns that lane's blob list, so existing
    per-group consumers keep working, but batch consumers iterate
    ``flat`` directly — no nested list-of-lists allocation per frame.
    ``unmarshal`` always returns this form."""

    __slots__ = ("flat", "starts")

    def __init__(self, flat: list[bytes], starts: np.ndarray):
        self.flat = flat
        self.starts = starts

    @classmethod
    def from_counts(cls, flat: list[bytes],
                    n_ents) -> "PackedPayloads":
        n = np.asarray(n_ents, np.int64)
        starts = np.zeros(n.shape[0] + 1, np.int64)
        np.cumsum(n, out=starts[1:])
        return cls(flat, starts)

    def __len__(self) -> int:
        return self.starts.shape[0] - 1

    def __getitem__(self, gi: int) -> list[bytes]:
        return self.flat[int(self.starts[gi]):
                         int(self.starts[gi + 1])]

    def __eq__(self, other) -> bool:
        if isinstance(other, PackedPayloads):
            return (self.flat == other.flat
                    and np.array_equal(self.starts, other.starts))
        if isinstance(other, (list, tuple)):
            return (len(other) == len(self)
                    and all(self[gi] == list(other[gi])
                            for gi in range(len(self))))
        return NotImplemented

    def __repr__(self) -> str:  # debugging aid only
        return f"PackedPayloads({len(self.flat)} blobs/{len(self)} groups)"


def flat_entry_table(prev_idx,
                     n_ents) -> tuple[np.ndarray, np.ndarray]:
    """Build the FLAG_PACKED (ent_group, ent_gindex) table for a
    frame carrying n_ents[g] entries per lane starting at
    prev_idx[g]+1 — all vectorized, no per-group host loop."""
    n = np.asarray(n_ents, np.int64)
    g = n.shape[0]
    total = int(n.sum())
    starts = np.zeros(g + 1, np.int64)
    np.cumsum(n, out=starts[1:])
    groups = np.repeat(np.arange(g, dtype=np.int32), n)
    j = np.arange(total, dtype=np.int64) - starts[groups]
    gindex = np.asarray(prev_idx, np.int64)[groups] + 1 + j
    return groups, gindex.astype(np.int32)


def _write_trace(buf: bytearray, pos: int, trace) -> int:
    struct.pack_into("<I", buf, pos, len(trace))
    pos += 4
    for g, gi, tid, org in trace:
        _TRACE_ENT.pack_into(buf, pos, g, gi, tid & 0xFFFFFFFF,
                             org & 0xFF)
        pos += _TRACE_ENT.size
    return pos


@dataclass
class AppendBatch:
    """Leader → follower replication round for all G groups at once
    (the batched msgApp, raft.proto:31-42 fields term/index/logTerm/
    entries/commit, G-wide).

    ``active[g]``: this frame carries an append for group g.
    ``need_snap[g]``: the leader has compacted past the follower's
    next index — follower must pull a full snapshot (the msgSnap
    analog, raft.go:207-209, as a pull to keep round frames small).
    ``ent_terms[g, j]``: term of entry prev_idx[g]+1+j, j < n_ents[g].
    ``payloads[g][j]``: that entry's opaque payload bytes.
    ``seq``/``epoch``: pipeline frame tags (module docstring).
    """

    sender: int
    term: np.ndarray        # [G] i32 leader term
    prev_idx: np.ndarray    # [G] i32
    prev_term: np.ndarray   # [G] i32
    n_ents: np.ndarray      # [G] i32
    commit: np.ndarray      # [G] i32 leader commit
    active: np.ndarray      # [G] bool
    need_snap: np.ndarray   # [G] bool
    ent_terms: np.ndarray   # [G, E] i32
    payloads: "list[list[bytes]] | PackedPayloads" = \
        field(default_factory=list)
    seq: int = 0
    epoch: int = 0
    #: optional distributed-trace block (PR 8): (group, gindex,
    #: trace_id, origin) per head-sampled entry this frame carries.
    #: None/[] marshals the exact pre-trace layout (flags=0).
    trace: list[tuple[int, int, int, int]] | None = None
    #: optional FLAG_PACKED flat entry table (PR 14): the group lane
    #: and absolute group index of each carried payload, frame order.
    #: Both or neither; None marshals the exact DGB2 layout.
    ent_group: np.ndarray | None = None   # [total] i32
    ent_gindex: np.ndarray | None = None  # [total] i32

    def marshal(self) -> bytearray:
        g = self.term.shape[0]
        e = self.ent_terms.shape[1] if self.ent_terms.size else 0
        n_ents = np.asarray(self.n_ents)
        flat: list[bytes]
        if isinstance(self.payloads, PackedPayloads):
            flat = self.payloads.flat
            if len(flat) != int(n_ents.sum()):
                raise FrameError("payloads disagree with n_ents")
        else:
            flat = []
            for gi in range(g):
                row = self.payloads[gi] if self.payloads else []
                for j in range(int(n_ents[gi])):
                    flat.append(row[j] if j < len(row) else b"")
        lens = [len(b) for b in flat]
        blob_total = sum(lens)
        trace = self.trace or None
        packed = self.ent_group is not None
        flags = ((FLAG_TRACE if trace else 0)
                 | (FLAG_PACKED if packed else 0))
        tr_bytes = (4 + _TRACE_ENT.size * len(trace)) if trace else 0
        pk_bytes = (4 + 8 * len(lens)) if packed else 0
        out = bytearray(_HDR.size + (5 * g + g * e + len(lens)) * 4
                        + 2 * g + blob_total + tr_bytes + pk_bytes)
        _HDR.pack_into(out, 0, _MAGIC, KIND_APPEND, self.sender,
                       flags, g, e, self.seq & 0xFFFFFFFF,
                       self.epoch & 0xFFFFFFFF)
        pos = _HDR.size
        pos = _w_i32(out, pos, self.term)
        pos = _w_i32(out, pos, self.prev_idx)
        pos = _w_i32(out, pos, self.prev_term)
        pos = _w_i32(out, pos, n_ents)
        pos = _w_i32(out, pos, self.commit)
        pos = _w_i32(out, pos, self.ent_terms)
        pos = _w_i32(out, pos, np.asarray(lens, "<i4"))
        pos = _w_u8(out, pos, self.active)
        pos = _w_u8(out, pos, self.need_snap)
        for b in flat:
            out[pos:pos + len(b)] = b
            pos += len(b)
        if trace:
            pos = _write_trace(out, pos, trace)
        if packed:
            struct.pack_into("<I", out, pos, len(lens))
            pos += 4
            pos = _w_i32(out, pos, self.ent_group)
            pos = _w_i32(out, pos, self.ent_gindex)
        return out

    @classmethod
    def unmarshal(cls, data) -> "AppendBatch":
        kind, sender, g, e, seq, epoch, flags = parse_header(data)
        if kind != KIND_APPEND:
            raise FrameError(f"kind {kind} != append")
        pos = _HDR.size
        term, pos = _view_i32(data, pos, g)
        prev_idx, pos = _view_i32(data, pos, g)
        prev_term, pos = _view_i32(data, pos, g)
        n_ents, pos = _view_i32(data, pos, g)
        commit, pos = _view_i32(data, pos, g)
        ets, pos = _view_i32(data, pos, g * e)
        if (n_ents < 0).any():
            # per-lane, not just the sum: one negative and one large
            # positive lane cancel to a small total but would spin
            # the payload loop for ~2^31 iterations before dying on
            # an IndexError instead of a FrameError
            raise FrameError("negative entry count")
        total = int(n_ents.sum())
        check_bound("dgb2.total_entries", total)
        lens, pos = _view_i32(data, pos, total)
        if total:
            check_bound("dgb2.payload_len", int(lens.max()))
        active, pos = _view_u8(data, pos, g)
        need_snap, pos = _view_u8(data, pos, g)
        buf = memoryview(data)
        # flat single-loop payload parse: blob order on the wire IS
        # frame order, so there is no per-group inner loop to run —
        # the nested view is recovered lazily via PackedPayloads
        flat: list[bytes] = []
        for li in range(total):
            ln = int(lens[li])
            if ln < 0 or pos + ln > len(data):
                raise FrameError("truncated payload blob")
            flat.append(bytes(buf[pos:pos + ln]))
            pos += ln
        payloads = PackedPayloads.from_counts(flat, n_ents)
        trace = None
        if flags & FLAG_TRACE:
            trace, pos = _read_trace(data, pos)
        ent_group = ent_gindex = None
        if flags & FLAG_PACKED:
            ent_group, ent_gindex, pos = _read_packed(
                data, pos, prev_idx, n_ents, total)
        return cls(sender=sender, term=term, prev_idx=prev_idx,
                   prev_term=prev_term, n_ents=n_ents, commit=commit,
                   active=active.astype(bool),
                   need_snap=need_snap.astype(bool),
                   ent_terms=ets.reshape(g, e), payloads=payloads,
                   seq=seq, epoch=epoch, trace=trace,
                   ent_group=ent_group, ent_gindex=ent_gindex)


@dataclass
class AppendResp:
    """Follower → leader batched msgAppResp.

    ``acked[g]``: on success, the follower's new match index; on
    reject, ignored.  ``hint[g]``: the follower's commit index — the
    leader repairs next_ to hint+1 on reject (faster than the
    reference's decrement-by-one probe, raft.go:464-470; safe because
    the committed prefix always matches).  ``seq``/``epoch`` echo the
    AppendBatch this responds to (pipeline ack matching)."""

    sender: int
    term: np.ndarray    # [G] i32 follower term (leader steps down if >)
    ok: np.ndarray      # [G] bool
    acked: np.ndarray   # [G] i32
    hint: np.ndarray    # [G] i32
    active: np.ndarray  # [G] bool
    seq: int = 0
    epoch: int = 0
    # LOCAL-ONLY (never marshalled): lanes whose entries the engine
    # actually appended this frame.  ``ok`` also covers need_snap
    # positive acks, which carry no entries — the follower's persist
    # loop must write exactly what was appended, so it iterates this
    # mask, not ``ok``.
    appended: np.ndarray | None = None

    def marshal(self) -> bytearray:
        g = self.term.shape[0]
        out = bytearray(_HDR.size + 3 * 4 * g + 2 * g)
        _HDR.pack_into(out, 0, _MAGIC, KIND_APPEND_RESP, self.sender,
                       0, g, 0, self.seq & 0xFFFFFFFF,
                       self.epoch & 0xFFFFFFFF)
        pos = _HDR.size
        pos = _w_i32(out, pos, self.term)
        pos = _w_i32(out, pos, self.acked)
        pos = _w_i32(out, pos, self.hint)
        pos = _w_u8(out, pos, self.ok)
        pos = _w_u8(out, pos, self.active)
        return out

    @classmethod
    def unmarshal(cls, data) -> "AppendResp":
        kind, sender, g, _e, seq, epoch, _flags = parse_header(data)
        if kind != KIND_APPEND_RESP:
            raise FrameError(f"kind {kind} != append_resp")
        pos = _HDR.size
        term, pos = _view_i32(data, pos, g)
        acked, pos = _view_i32(data, pos, g)
        hint, pos = _view_i32(data, pos, g)
        ok, pos = _view_u8(data, pos, g)
        active, pos = _view_u8(data, pos, g)
        return cls(sender=sender, term=term, ok=ok.astype(bool),
                   acked=acked, hint=hint,
                   active=active.astype(bool), seq=seq, epoch=epoch)


@dataclass
class VoteReq:
    """Candidate → peer batched msgVote (raft.go:363-369)."""

    sender: int
    term: np.ndarray    # [G] i32 candidate term
    last: np.ndarray    # [G] i32 candidate last index
    lterm: np.ndarray   # [G] i32 candidate last term
    active: np.ndarray  # [G] bool

    def marshal(self) -> bytearray:
        g = self.term.shape[0]
        out = bytearray(_HDR.size + 3 * 4 * g + g)
        _HDR.pack_into(out, 0, _MAGIC, KIND_VOTE, self.sender, 0,
                       g, 0, 0, 0)
        pos = _HDR.size
        pos = _w_i32(out, pos, self.term)
        pos = _w_i32(out, pos, self.last)
        pos = _w_i32(out, pos, self.lterm)
        pos = _w_u8(out, pos, self.active)
        return out

    @classmethod
    def unmarshal(cls, data) -> "VoteReq":
        kind, sender, g, _e, _seq, _epoch, _fl = parse_header(data)
        if kind != KIND_VOTE:
            raise FrameError(f"kind {kind} != vote")
        pos = _HDR.size
        term, pos = _view_i32(data, pos, g)
        last, pos = _view_i32(data, pos, g)
        lterm, pos = _view_i32(data, pos, g)
        active, pos = _view_u8(data, pos, g)
        return cls(sender=sender, term=term, last=last, lterm=lterm,
                   active=active.astype(bool))


@dataclass
class VoteResp:
    """Peer → candidate batched msgVoteResp."""

    sender: int
    term: np.ndarray     # [G] i32 responder term
    granted: np.ndarray  # [G] bool
    active: np.ndarray   # [G] bool

    def marshal(self) -> bytearray:
        g = self.term.shape[0]
        out = bytearray(_HDR.size + 4 * g + 2 * g)
        _HDR.pack_into(out, 0, _MAGIC, KIND_VOTE_RESP, self.sender,
                       0, g, 0, 0, 0)
        pos = _HDR.size
        pos = _w_i32(out, pos, self.term)
        pos = _w_u8(out, pos, self.granted)
        pos = _w_u8(out, pos, self.active)
        return out

    @classmethod
    def unmarshal(cls, data) -> "VoteResp":
        kind, sender, g, _e, _seq, _epoch, _fl = parse_header(data)
        if kind != KIND_VOTE_RESP:
            raise FrameError(f"kind {kind} != vote_resp")
        pos = _HDR.size
        term, pos = _view_i32(data, pos, g)
        granted, pos = _view_u8(data, pos, g)
        active, pos = _view_u8(data, pos, g)
        return cls(sender=sender, term=term,
                   granted=granted.astype(bool),
                   active=active.astype(bool))


def unmarshal_any(data):
    kind, *_ = parse_header(data)
    try:
        cls = {KIND_APPEND: AppendBatch,
               KIND_APPEND_RESP: AppendResp,
               KIND_VOTE: VoteReq,
               KIND_VOTE_RESP: VoteResp}[kind]
    except KeyError:
        raise FrameError(f"unknown frame kind {kind}") from None
    return cls.unmarshal(data)
