"""Batched inter-host consensus frames for the distributed
multi-group server (SURVEY §5.8's DCN tier).

The reference's peer transport ships ONE raftpb.Message per HTTP POST
(etcdserver/cluster_store.go:106-156).  The distributed multi-group
server hosts one member slot of ALL G co-hosted groups per process,
so a replication round produces G messages *per peer* — shipped here
as ONE binary frame of [G] arrays (the batched analog: same
fire-and-forget, drop-tolerant contract, server.go:202-206, but the
unit of transport is the whole group batch).

Frame = 16-byte header + fixed [G] sections + payload table:

  header:  magic "DGB1" | kind u8 | sender_slot u8 | flags u16 |
           g u32 | e u32
  body:    kind-specific little-endian arrays (see each class)
  payload: lens [sum(n_ents)] i32 + concatenated blobs (appends only)

Arrays are raw numpy little-endian — the receiving end feeds them
straight into the batched engine (raft/batched.py) without a decode
loop: wire layout == device layout is the point.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

_MAGIC = b"DGB1"
_HDR = struct.Struct("<4sBBHII")

KIND_APPEND = 0
KIND_APPEND_RESP = 1
KIND_VOTE = 2
KIND_VOTE_RESP = 3
KIND_PROPOSE = 4


class FrameError(Exception):
    pass


def _i32(g: int, buf: memoryview, pos: int) -> tuple[np.ndarray, int]:
    end = pos + 4 * g
    if end > len(buf):
        raise FrameError("truncated i32 section")
    return np.frombuffer(buf[pos:end], "<i4").copy(), end


def _u8(g: int, buf: memoryview, pos: int) -> tuple[np.ndarray, int]:
    end = pos + g
    if end > len(buf):
        raise FrameError("truncated u8 section")
    return np.frombuffer(buf[pos:end], np.uint8).copy(), end


def _header(kind: int, sender: int, g: int, e: int = 0) -> bytes:
    return _HDR.pack(_MAGIC, kind, sender, 0, g, e)


def parse_header(data: bytes) -> tuple[int, int, int, int]:
    """Returns (kind, sender_slot, g, e); raises FrameError."""
    if len(data) < _HDR.size:
        raise FrameError("short frame")
    magic, kind, sender, _flags, g, e = _HDR.unpack_from(data)
    if magic != _MAGIC:
        raise FrameError("bad magic")
    return kind, sender, g, e


@dataclass
class AppendBatch:
    """Leader → follower replication round for all G groups at once
    (the batched msgApp, raft.proto:31-42 fields term/index/logTerm/
    entries/commit, G-wide).

    ``active[g]``: this frame carries an append for group g.
    ``need_snap[g]``: the leader has compacted past the follower's
    next index — follower must pull a full snapshot (the msgSnap
    analog, raft.go:207-209, as a pull to keep round frames small).
    ``ent_terms[g, j]``: term of entry prev_idx[g]+1+j, j < n_ents[g].
    ``payloads[g][j]``: that entry's opaque payload bytes.
    """

    sender: int
    term: np.ndarray        # [G] i32 leader term
    prev_idx: np.ndarray    # [G] i32
    prev_term: np.ndarray   # [G] i32
    n_ents: np.ndarray      # [G] i32
    commit: np.ndarray      # [G] i32 leader commit
    active: np.ndarray      # [G] bool
    need_snap: np.ndarray   # [G] bool
    ent_terms: np.ndarray   # [G, E] i32
    payloads: list[list[bytes]] = field(default_factory=list)

    def marshal(self) -> bytes:
        g = self.term.shape[0]
        e = self.ent_terms.shape[1] if self.ent_terms.size else 0
        lens, blobs = [], []
        for gi in range(g):
            row = self.payloads[gi] if self.payloads else []
            for j in range(int(self.n_ents[gi])):
                b = row[j] if j < len(row) else b""
                lens.append(len(b))
                blobs.append(b)
        return b"".join([
            _header(KIND_APPEND, self.sender, g, e),
            np.asarray(self.term, "<i4").tobytes(),
            np.asarray(self.prev_idx, "<i4").tobytes(),
            np.asarray(self.prev_term, "<i4").tobytes(),
            np.asarray(self.n_ents, "<i4").tobytes(),
            np.asarray(self.commit, "<i4").tobytes(),
            np.asarray(self.active, np.uint8).tobytes(),
            np.asarray(self.need_snap, np.uint8).tobytes(),
            np.ascontiguousarray(self.ent_terms, "<i4").tobytes(),
            np.asarray(lens, "<i4").tobytes(),
        ] + blobs)

    @classmethod
    def unmarshal(cls, data: bytes) -> "AppendBatch":
        kind, sender, g, e = parse_header(data)
        if kind != KIND_APPEND:
            raise FrameError(f"kind {kind} != append")
        buf = memoryview(data)
        pos = _HDR.size
        term, pos = _i32(g, buf, pos)
        prev_idx, pos = _i32(g, buf, pos)
        prev_term, pos = _i32(g, buf, pos)
        n_ents, pos = _i32(g, buf, pos)
        commit, pos = _i32(g, buf, pos)
        active, pos = _u8(g, buf, pos)
        need_snap, pos = _u8(g, buf, pos)
        ets, pos = _i32(g * e, buf, pos)
        total = int(n_ents.sum())
        lens, pos = _i32(total, buf, pos)
        payloads: list[list[bytes]] = []
        li = 0
        for gi in range(g):
            row = []
            for _ in range(int(n_ents[gi])):
                ln = int(lens[li])
                li += 1
                row.append(bytes(buf[pos:pos + ln]))
                pos += ln
            payloads.append(row)
        return cls(sender=sender, term=term, prev_idx=prev_idx,
                   prev_term=prev_term, n_ents=n_ents, commit=commit,
                   active=active.astype(bool),
                   need_snap=need_snap.astype(bool),
                   ent_terms=ets.reshape(g, e), payloads=payloads)


@dataclass
class AppendResp:
    """Follower → leader batched msgAppResp.

    ``acked[g]``: on success, the follower's new match index; on
    reject, ignored.  ``hint[g]``: the follower's commit index — the
    leader repairs next_ to hint+1 on reject (faster than the
    reference's decrement-by-one probe, raft.go:464-470; safe because
    the committed prefix always matches)."""

    sender: int
    term: np.ndarray    # [G] i32 follower term (leader steps down if >)
    ok: np.ndarray      # [G] bool
    acked: np.ndarray   # [G] i32
    hint: np.ndarray    # [G] i32
    active: np.ndarray  # [G] bool
    # LOCAL-ONLY (never marshalled): lanes whose entries the engine
    # actually appended this frame.  ``ok`` also covers need_snap
    # positive acks, which carry no entries — the follower's persist
    # loop must write exactly what was appended, so it iterates this
    # mask, not ``ok``.
    appended: np.ndarray | None = None

    def marshal(self) -> bytes:
        g = self.term.shape[0]
        return b"".join([
            _header(KIND_APPEND_RESP, self.sender, g),
            np.asarray(self.term, "<i4").tobytes(),
            np.asarray(self.ok, np.uint8).tobytes(),
            np.asarray(self.acked, "<i4").tobytes(),
            np.asarray(self.hint, "<i4").tobytes(),
            np.asarray(self.active, np.uint8).tobytes(),
        ])

    @classmethod
    def unmarshal(cls, data: bytes) -> "AppendResp":
        kind, sender, g, _ = parse_header(data)
        if kind != KIND_APPEND_RESP:
            raise FrameError(f"kind {kind} != append_resp")
        buf = memoryview(data)
        pos = _HDR.size
        term, pos = _i32(g, buf, pos)
        ok, pos = _u8(g, buf, pos)
        acked, pos = _i32(g, buf, pos)
        hint, pos = _i32(g, buf, pos)
        active, pos = _u8(g, buf, pos)
        return cls(sender=sender, term=term, ok=ok.astype(bool),
                   acked=acked, hint=hint, active=active.astype(bool))


@dataclass
class VoteReq:
    """Candidate → peer batched msgVote (raft.go:363-369)."""

    sender: int
    term: np.ndarray    # [G] i32 candidate term
    last: np.ndarray    # [G] i32 candidate last index
    lterm: np.ndarray   # [G] i32 candidate last term
    active: np.ndarray  # [G] bool

    def marshal(self) -> bytes:
        g = self.term.shape[0]
        return b"".join([
            _header(KIND_VOTE, self.sender, g),
            np.asarray(self.term, "<i4").tobytes(),
            np.asarray(self.last, "<i4").tobytes(),
            np.asarray(self.lterm, "<i4").tobytes(),
            np.asarray(self.active, np.uint8).tobytes(),
        ])

    @classmethod
    def unmarshal(cls, data: bytes) -> "VoteReq":
        kind, sender, g, _ = parse_header(data)
        if kind != KIND_VOTE:
            raise FrameError(f"kind {kind} != vote")
        buf = memoryview(data)
        pos = _HDR.size
        term, pos = _i32(g, buf, pos)
        last, pos = _i32(g, buf, pos)
        lterm, pos = _i32(g, buf, pos)
        active, pos = _u8(g, buf, pos)
        return cls(sender=sender, term=term, last=last, lterm=lterm,
                   active=active.astype(bool))


@dataclass
class VoteResp:
    """Peer → candidate batched msgVoteResp."""

    sender: int
    term: np.ndarray     # [G] i32 responder term
    granted: np.ndarray  # [G] bool
    active: np.ndarray   # [G] bool

    def marshal(self) -> bytes:
        g = self.term.shape[0]
        return b"".join([
            _header(KIND_VOTE_RESP, self.sender, g),
            np.asarray(self.term, "<i4").tobytes(),
            np.asarray(self.granted, np.uint8).tobytes(),
            np.asarray(self.active, np.uint8).tobytes(),
        ])

    @classmethod
    def unmarshal(cls, data: bytes) -> "VoteResp":
        kind, sender, g, _ = parse_header(data)
        if kind != KIND_VOTE_RESP:
            raise FrameError(f"kind {kind} != vote_resp")
        buf = memoryview(data)
        pos = _HDR.size
        term, pos = _i32(g, buf, pos)
        granted, pos = _u8(g, buf, pos)
        active, pos = _u8(g, buf, pos)
        return cls(sender=sender, term=term,
                   granted=granted.astype(bool),
                   active=active.astype(bool))


def unmarshal_any(data: bytes):
    kind, *_ = parse_header(data)
    return {KIND_APPEND: AppendBatch,
            KIND_APPEND_RESP: AppendResp,
            KIND_VOTE: VoteReq,
            KIND_VOTE_RESP: VoteResp}[kind].unmarshal(data)
