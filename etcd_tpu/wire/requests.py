"""Request/Info wire types (reference etcdserver/etcdserverpb/
etcdserver.proto) — the payload of every replicated log entry.

``prev_exist`` is the only nullable field (a *bool in the reference):
None omits field 8 entirely, matching the generated marshaler.
Int64 fields (expiration, time) are encoded as their two's-complement
uint64 varints, as protobuf requires.
"""

from __future__ import annotations

from dataclasses import dataclass

from .proto import (
    ProtoError,
    _bytes_field,
    _expect_wt,
    _skip_field,
    _tagged_varint,
    put_uvarint,
    uvarint,
)

_MASK64 = (1 << 64) - 1


def _to_i64(u: int) -> int:
    """uint64 wire value -> python int with int64 semantics."""
    return u - (1 << 64) if u >= (1 << 63) else u


def _tagged_string(buf: bytearray, tag: int, s: str) -> None:
    b = s.encode()
    buf.append(tag)
    put_uvarint(buf, len(b))
    buf.extend(b)


def _string_field(data: bytes, pos: int) -> tuple[str, int]:
    """Length-delimited utf-8 field; a non-utf-8 blob fails typed as
    ProtoError, never an escaping UnicodeDecodeError (these payloads
    arrive over the FWD_REQ handoff and out of the WAL)."""
    b, pos = _bytes_field(data, pos)
    try:
        return b.decode(), pos
    except UnicodeDecodeError:
        raise ProtoError("string field not utf-8") from None


@dataclass(slots=True)
class Request:
    id: int = 0
    method: str = ""
    path: str = ""
    val: str = ""
    dir: bool = False
    prev_value: str = ""
    prev_index: int = 0
    prev_exist: bool | None = None
    expiration: int = 0  # unix nanos
    wait: bool = False
    since: int = 0
    recursive: bool = False
    sorted: bool = False
    quorum: bool = False
    time: int = 0  # unix nanos
    stream: bool = False
    # LOCAL-ONLY (never marshaled): reads don't enter the log, so
    # the serializable opt-out (PR 7 consistency knob) stays a
    # process-local routing hint — adding it to the wire form would
    # perturb every persisted entry's bytes for a field no replica
    # ever needs.
    serializable: bool = False

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.id)
        _tagged_string(buf, 0x12, self.method)
        _tagged_string(buf, 0x1A, self.path)
        _tagged_string(buf, 0x22, self.val)
        _tagged_varint(buf, 0x28, 1 if self.dir else 0)
        _tagged_string(buf, 0x32, self.prev_value)
        _tagged_varint(buf, 0x38, self.prev_index)
        if self.prev_exist is not None:
            _tagged_varint(buf, 0x40, 1 if self.prev_exist else 0)
        _tagged_varint(buf, 0x48, self.expiration & _MASK64)
        _tagged_varint(buf, 0x50, 1 if self.wait else 0)
        _tagged_varint(buf, 0x58, self.since)
        _tagged_varint(buf, 0x60, 1 if self.recursive else 0)
        _tagged_varint(buf, 0x68, 1 if self.sorted else 0)
        _tagged_varint(buf, 0x70, 1 if self.quorum else 0)
        _tagged_varint(buf, 0x78, self.time & _MASK64)
        buf.append(0x80)
        buf.append(0x01)
        put_uvarint(buf, 1 if self.stream else 0)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Request":
        r = cls()
        pos = 0
        while pos < len(data):
            tag, pos = uvarint(data, pos)
            fnum, wt = tag >> 3, tag & 7
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                r.id, pos = uvarint(data, pos)
            elif fnum == 2:
                _expect_wt(fnum, wt, 2)
                r.method, pos = _string_field(data, pos)
            elif fnum == 3:
                _expect_wt(fnum, wt, 2)
                r.path, pos = _string_field(data, pos)
            elif fnum == 4:
                _expect_wt(fnum, wt, 2)
                r.val, pos = _string_field(data, pos)
            elif fnum == 5:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.dir = bool(v)
            elif fnum == 6:
                _expect_wt(fnum, wt, 2)
                r.prev_value, pos = _string_field(data, pos)
            elif fnum == 7:
                _expect_wt(fnum, wt, 0)
                r.prev_index, pos = uvarint(data, pos)
            elif fnum == 8:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.prev_exist = bool(v)
            elif fnum == 9:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.expiration = _to_i64(v)
            elif fnum == 10:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.wait = bool(v)
            elif fnum == 11:
                _expect_wt(fnum, wt, 0)
                r.since, pos = uvarint(data, pos)
            elif fnum == 12:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.recursive = bool(v)
            elif fnum == 13:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.sorted = bool(v)
            elif fnum == 14:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.quorum = bool(v)
            elif fnum == 15:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.time = _to_i64(v)
            elif fnum == 16:
                _expect_wt(fnum, wt, 0)
                v, pos = uvarint(data, pos)
                r.stream = bool(v)
            else:
                pos = _skip_field(data, pos, wt)
        return r


@dataclass(slots=True)
class Info:
    """WAL metadata payload (etcdserver.proto:30-32)."""

    id: int = 0

    def marshal(self) -> bytes:
        buf = bytearray()
        _tagged_varint(buf, 0x08, self.id)
        return bytes(buf)

    @classmethod
    def unmarshal(cls, data: bytes) -> "Info":
        info = cls()
        pos = 0
        while pos < len(data):
            tag, pos = uvarint(data, pos)
            fnum, wt = tag >> 3, tag & 7
            if fnum == 1:
                _expect_wt(fnum, wt, 0)
                info.id, pos = uvarint(data, pos)
            else:
                pos = _skip_field(data, pos, wt)
        return info
