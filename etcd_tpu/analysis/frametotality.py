"""frame-totality: parse paths fail typed, and the schema's frame
vocabulary is handled totally.

The wire contract (every parser module's docstring, fuzz-enforced by
scripts/wire_fuzz.py) is that a malformed frame surfaces as the
format's ONE typed error — ``FrameError`` for the frame formats,
``ProtoError`` for the codec — never as a raw ``struct.error``,
``IndexError``, ``UnicodeDecodeError``, or ``ValueError`` escaping
into a serving loop that only catches the typed family.  This checker
is the static half of that contract:

  * ``unguarded-unpack`` — a ``struct`` unpack in a parse scope with
    no dominating raising length check and no enclosing
    ``struct.error`` handler that re-raises typed.
  * ``untyped-decode`` — ``.decode()`` / ``str(b, "utf-8")`` /
    ``json.loads`` in a parse scope outside a try/except that
    catches the decoding failure and re-raises typed.
  * ``unhandled-kind`` — a schema frame kind whose unmarshal scope
    exists but never references its ``KIND_`` constant (the
    ``kind != KIND_X`` rejection was dropped in a refactor).
  * ``missing-unknown-kind-rejection`` — a module dispatching on
    schema kinds with no typed rejection of the unknown case.
  * ``unhandled-flag`` — a schema flag bit with a declared parse
    scope that never tests it (its gated trailing section would be
    silently misparsed as another section's bytes).
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions
from .wiremodel import (SCHEMA_RELPATH, WIRE_TARGETS, module_schema,
                        parse_scopes, typed_error)

#: exception names acceptable as the typed re-raise family
_TYPED = {"FrameError", "ProtoError"}

#: what an enclosing handler must catch for each untyped decoder
_DECODE_CATCHES = {
    "decode": {"UnicodeDecodeError", "ValueError", "Exception"},
    "str": {"UnicodeDecodeError", "ValueError", "Exception"},
    "json.loads": {"ValueError", "KeyError", "TypeError",
                   "Exception"},
}
_UNPACK_CATCHES = {"error", "struct.error", "Exception"}


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:
        return {"Exception"}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for e in elts:
        d = dotted_name(e)
        if d:
            out.add(d)
            out.add(d.rsplit(".", 1)[-1])
    return out


def _raises_typed(body: list[ast.stmt], typed: str) -> bool:
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Raise) and n.exc is not None:
                exc = n.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                name = dotted_name(exc).rsplit(".", 1)[-1]
                if name == typed or name in _TYPED:
                    return True
    return False


def _decoder_kind(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "decode":
        return "decode"
    d = dotted_name(f)
    if d.rsplit(".", 1)[-1] == "loads":
        return "json.loads"
    if isinstance(f, ast.Name) and f.id == "str" \
            and len(node.args) >= 2:
        return "str"
    return None


def _is_unpack(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in ("unpack_from", "unpack"))


class FrameTotalityChecker(Checker):
    name = "frame-totality"
    targets = WIRE_TARGETS

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        if relpath == SCHEMA_RELPATH:
            return []
        out: list[Finding] = []
        typed = typed_error(relpath)
        scopes = parse_scopes(relpath, tree, ctx)
        for scope, fn in scopes.items():
            self._check_scope(relpath, scope, fn, typed, out)
        self._check_vocabulary(relpath, tree, scopes, typed, out)
        return out

    # -- per-scope: untyped escape routes -------------------------------

    def _check_scope(self, relpath: str, scope: str, fn: ast.AST,
                     typed: str, out: list[Finding]) -> None:
        guard_lines = [
            n.lineno for n in ast.walk(fn)
            if isinstance(n, ast.If)
            and any(isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Name)
                    and c.func.id == "len"
                    for c in ast.walk(n.test))
            and any(isinstance(b, (ast.Raise, ast.Return))
                    for s in n.body for b in ast.walk(s))]

        def walk(node: ast.AST, catches: frozenset[str]) -> None:
            if isinstance(node, ast.Try):
                inner = catches
                good = frozenset(
                    name for h in node.handlers
                    if _raises_typed(h.body, typed)
                    for name in _handler_names(h))
                if good:
                    inner = catches | good
                for s in node.body:
                    walk(s, inner)
                for h in node.handlers:
                    for s in h.body:
                        walk(s, catches)
                for s in node.orelse + node.finalbody:
                    walk(s, catches)
                return
            if isinstance(node, ast.Call):
                kind = _decoder_kind(node)
                if kind is not None \
                        and not (catches & _DECODE_CATCHES[kind]):
                    out.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="untyped-decode",
                        scope=scope,
                        message=f"{kind} on wire bytes can escape "
                                f"untyped — wrap in try/except and "
                                f"re-raise {typed}",
                        detail=kind))
                elif _is_unpack(node) \
                        and not (catches & _UNPACK_CATCHES) \
                        and not any(ln < node.lineno
                                    for ln in guard_lines):
                    out.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="unguarded-unpack",
                        scope=scope,
                        message=f"struct unpack with no dominating "
                                f"raising len() check and no "
                                f"struct.error handler — truncation "
                                f"escapes as struct.error, not "
                                f"{typed}",
                        detail=dotted_name(node.func)
                        or "unpack"))
            for child in ast.iter_child_nodes(node):
                walk(child, catches)

        for stmt in fn.body:
            walk(stmt, frozenset())

    # -- whole-module: total handling of the declared vocabulary --------

    def _check_vocabulary(self, relpath: str, tree: ast.AST,
                          scopes: dict[str, ast.AST], typed: str,
                          out: list[Finding]) -> None:
        sch = module_schema(relpath)
        if sch is None:
            return
        funcs = dict(iter_functions(tree))
        refs_kind = False
        for kind in sch.kinds:
            if not kind.unmarshal:
                continue
            fn = funcs.get(kind.unmarshal)
            if fn is None:
                continue
            refs_kind = True
            if not any(isinstance(n, ast.Name) and n.id == kind.name
                       for n in ast.walk(fn)):
                out.append(Finding(
                    checker=self.name, path=relpath, line=fn.lineno,
                    rule="unhandled-kind", scope=kind.unmarshal,
                    message=f"{kind.unmarshal} never checks "
                            f"{kind.name} — a frame of another kind "
                            f"would be parsed as this one's "
                            f"sections",
                    detail=kind.name))
        if refs_kind and scopes \
                and not self._rejects_unknown_kind(tree, typed):
            out.append(Finding(
                checker=self.name, path=relpath, line=1,
                rule="missing-unknown-kind-rejection", scope="",
                message=f"module dispatches on {sch.name} frame "
                        f"kinds but never rejects an unknown kind "
                        f"with {typed}",
                detail=sch.name))
        for flag in sch.flags:
            if not flag.scope:
                continue  # carried for a downstream consumer
            fn = funcs.get(flag.scope)
            if fn is None:
                continue
            if not any(isinstance(n, ast.Name)
                       and n.id == flag.name
                       for n in ast.walk(fn)):
                out.append(Finding(
                    checker=self.name, path=relpath, line=fn.lineno,
                    rule="unhandled-flag", scope=flag.scope,
                    message=f"{flag.scope} never tests {flag.name} "
                            f"— its gated trailing section would be "
                            f"misparsed or silently dropped",
                    detail=flag.name))

    @staticmethod
    def _rejects_unknown_kind(tree: ast.AST, typed: str) -> bool:
        for n in ast.walk(tree):
            if isinstance(n, ast.ExceptHandler) \
                    and "KeyError" in _handler_names(n) \
                    and _raises_typed(n.body, typed):
                return True
            if isinstance(n, ast.If) \
                    and any(isinstance(t, ast.Name)
                            and "kind" in t.id.lower()
                            for t in ast.walk(n.test)) \
                    and _raises_typed(n.body, typed):
                return True
        return False
