"""metrics-vocabulary: registry accessor names must be in the catalog.

The obs registry already raises ``KeyError`` at runtime for a name
missing from ``obs/metrics.py``'s CATALOG — but only when the code
path executes.  This checker moves that to lint time: every
``<registry-ish>.counter("...")`` / ``.gauge("...")`` /
``.histogram("...")`` call with a string-literal name must name a
registered family, and a *dynamic* (non-literal) name on a
registry-ish receiver is flagged too, because it defeats both this
check and the README's metric inventory.

"Registry-ish" receivers: the final attribute/name segment is one of
``registry`` / ``obs_registry`` / ``reg`` / ``_reg`` (the repo's
binding conventions), or the name literal itself starts with
``etcd_`` (the catalog's namespace) — so an accessor call on any
receiver that *tries* to mint an ``etcd_*`` metric is checked.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map

_ACCESSORS = {"counter", "gauge", "histogram"}
_RECEIVERS = {"registry", "obs_registry", "reg", "_reg", "_obs"}


class MetricsVocabularyChecker(Checker):
    name = "metrics-vocabulary"
    targets = ("etcd_tpu/", "scripts/", "bench.py")

    def _catalog(self) -> set[str] | None:
        try:
            from ..obs.metrics import CATALOG

            return set(CATALOG)
        except Exception:  # pragma: no cover - bootstrap order
            return None

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        if relpath == "etcd_tpu/obs/metrics.py":
            return []  # the catalog itself
        catalog = self._catalog()
        if catalog is None:  # pragma: no cover
            return []
        owner = scope_map(tree)
        out: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _ACCESSORS:
                continue
            recv = dotted_name(func.value)
            recv_last = recv.rsplit(".", 1)[-1] if recv else ""
            literal = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                literal = node.args[0].value
            registryish = recv_last in _RECEIVERS or (
                literal is not None and literal.startswith("etcd_"))
            if not registryish:
                continue
            scope = owner.get(node, "")
            if literal is None:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="dynamic-metric-name",
                    scope=scope,
                    message=f"{recv}.{func.attr}(<non-literal>) — "
                            f"metric names must be string literals "
                            f"from obs/metrics.py's CATALOG",
                    detail=f"{recv_last}.{func.attr}"))
            elif literal not in catalog:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="unregistered-metric",
                    scope=scope,
                    message=f"metric {literal!r} is not registered "
                            f"in obs/metrics.py's CATALOG",
                    detail=literal))
        return out
