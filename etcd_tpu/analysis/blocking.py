"""Blocking-under-lock checker (PR 16 tentpole, part 2).

The static form of the bug class PR 6 found dynamically (snapshot
serialization stalling every handler under the server lock) and
PR 12 re-found in the frontdoor (one wedged loop thread starves all
tenants): a *blocking* operation executed while a hot-path lock is
held turns one slow syscall into a cluster-wide convoy.

Blocking categories (from the shared concurrency model):

- ``fsio``    — ``os.fsync`` / ``os.fdatasync`` / ``.fsync()``
- ``socket``  — ``.sendall`` / ``.recv`` / ``.accept`` /
  ``.connect`` / ``socket.create_connection``
- ``sleep``   — ``time.sleep``
- ``queue``   — blocking ``queue.get`` (and ``put`` on a *bounded*
  queue; puts to unbounded queues never block)
- ``subprocess`` — any ``subprocess.*`` spawn
- ``jit-dispatch`` — a call that reaches a ``@jax.jit`` root (the
  purity walk's dispatch roots): first-call tracing can take
  seconds

An operation is flagged when a HOT lock is lexically held at the
site **or** may be held at entry to the containing function (union
propagation over call edges — the callee form "helper does the
fsync, caller holds the lock" is the common shape).  Only the hot
set below is enforced; cold, short-critical-section locks (metrics
counters, backoff state) may guard whatever they like.

Suppress a deliberate case with ``# lint: ok(blocking-under-lock)``
on the flagged line, or baseline it with a justification (e.g. the
WAL fsync under the DistServer lock *is* the persist-before-ack
durability contract).
"""

from __future__ import annotations

import ast

from .concmodel import concurrency_model
from .engine import AnalysisContext, Checker, Finding

#: locks whose critical sections sit on serving hot paths
HOT_LOCKS = frozenset({
    "Store.world_lock",    # every read/write/watch touches it
    "WatcherHub.mutex",    # watcher tables + history scans
    "DistServer.lock",     # raft state; all peer + client traffic
    "FrontDoor._lock",     # loop<->worker mailbox; loop liveness
    "WorkerEtcd.lock",     # role-split worker mirror store
    "_Stripe.cond",        # peerlink channel stripes
    "KeepAlivePool._lock",  # shared conn pool on the send path
})

#: (lock, category) pairs that are the DESIGN, not a bug — allowed
#: in code rather than via N identical baseline entries.  Today:
#: every raft step (tick/append/vote/commit) IS a jit dispatch
#: executed under the server lock — the lock exists precisely to
#: serialize those device-state transitions, and steady-state
#: dispatch is a warmed cache hit, not a trace.  fsio under the
#: same lock is NOT allowed here: the WAL-fsync sites are
#: individually baselined so a *new* fsync-under-lock still fails
#: the gate.
ALLOWED_PAIRS = frozenset({
    ("DistServer.lock", "jit-dispatch"),
})


class BlockingUnderLockChecker(Checker):
    name = "blocking-under-lock"
    targets = ("etcd_tpu/",)

    def __init__(self, hot_locks: frozenset = HOT_LOCKS,
                 allowed_pairs: frozenset = ALLOWED_PAIRS):
        self.hot_locks = hot_locks
        self.allowed_pairs = allowed_pairs
        self._cache: dict[str, dict[str, list[Finding]]] = {}

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None,
              ctx: AnalysisContext | None = None) -> list[Finding]:
        if root is None or ctx is None:
            return []
        by_file = self._cache.get(root)
        if by_file is None:
            by_file = self._analyze(root, ctx)
            self._cache[root] = by_file
        return list(by_file.get(relpath, ()))

    # ------------------------------------------------------------------

    def _analyze(self, root: str,
                 ctx: AnalysisContext) -> dict[str, list[Finding]]:
        model = concurrency_model(root, ctx)
        entry = model.entry_held_union(self.hot_locks)

        by_file: dict[str, list[Finding]] = {}
        seen: set[tuple] = set()
        for key, fi in model.functions.items():
            if fi.scope.split(".")[-1] == "__init__":
                continue
            inherited = entry.get(key, frozenset())
            for cat, op, held, line in fi.blocking:
                lexical = frozenset(held) & self.hot_locks
                for lock in sorted(lexical | inherited):
                    if (lock, cat) in self.allowed_pairs:
                        continue
                    detail = f"{lock}|{op}"
                    dedup = (fi.relpath, fi.scope, detail)
                    if dedup in seen:
                        continue
                    seen.add(dedup)
                    how = ("under" if lock in lexical
                           else "reachable with")
                    by_file.setdefault(fi.relpath, []).append(
                        Finding(
                            checker=self.name, path=fi.relpath,
                            line=line, rule=f"blocking-{cat}",
                            scope=fi.scope, detail=detail,
                            message=(f"blocking op {op} ({cat}) "
                                     f"{how} hot lock {lock} "
                                     f"held")))
        return by_file
