"""error-vocabulary: raises on the client-visible tier must resolve
to the numeric vocabulary (utils/errors.py) or an allow-listed
internal type.

The reference maps every client-visible failure to a numeric code
(error/error.go); this tree keeps that vocabulary in
``utils/errors.py``.  In ``api/``, ``server/``, ``store/``:

- ``raise EtcdError(<code>, ...)`` (or the ``bad(<code>, ...)``
  helper): ``<code>`` must be an ``ECODE_*`` name defined in
  utils/errors.py or an integer literal in the vocabulary — an
  unknown code would serialize as "unknown error" to clients.
- ``raise <InternalType>(...)``: the type must be allow-listed
  (typed control-flow exceptions the HTTP layer translates, plus
  stdlib programming-error types).  ``raise Exception(...)`` or an
  unknown type is a finding — it reaches clients as an opaque 500.
- Bare ``raise`` and re-raising a captured variable are always fine.
"""

from __future__ import annotations

import ast
import os

from .engine import Checker, Finding, dotted_name, iter_functions

#: constructors that take a numeric vocabulary code as first arg
_VOCAB_CTORS = {"EtcdError", "bad"}

#: exception types allowed outside the numeric vocabulary: typed
#: internal control flow the API layer translates, plus stdlib
#: programming-error types that indicate caller bugs, not etcd state
_ALLOWED = {
    # repo-internal typed exceptions
    "UnknownMethodError", "ServerStoppedError", "ClientError",
    "StoppedError", "RaftPanicError", "WALError", "TornTailError",
    "FileNotFoundError_", "SnapError", "NoSnapshotError",
    "ProtoError", "FrameError", "DiscoveryError", "ClusterFullError",
    # PR 10: EtcdNoSpace carries ECODE_NO_SPACE (an EtcdError
    # subclass — listed for the bare-raise form); FrameDropped is
    # the injected-loss control exception the peer handler turns
    # into a closed connection
    "EtcdNoSpace", "FrameDropped",
    # PR 15: EtcdOverCapacity carries ECODE_OVER_CAPACITY (same
    # vocabulary-subclass pattern as EtcdNoSpace) — the ingest
    # role raises it when a shard lane sheds
    "EtcdOverCapacity",
    # stdlib
    "ValueError", "TypeError", "KeyError", "IndexError",
    "AttributeError", "RuntimeError", "TimeoutError",
    "AssertionError", "NotImplementedError", "OSError",
    "FileExistsError", "FileNotFoundError", "InterruptedError",
    "StopIteration", "ConnectionError",
}

_VOCAB_RELPATH = "etcd_tpu/utils/errors.py"


def _load_vocab(root: str) -> tuple[set[str], set[int]]:
    """(ECODE_* names, numeric values) from utils/errors.py."""
    names: set[str] = set()
    values: set[int] = set()
    path = os.path.join(root or ".", _VOCAB_RELPATH)
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except OSError:
        return names, values
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("ECODE_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            names.add(node.targets[0].id)
            values.add(node.value.value)
    return names, values


class ErrorVocabularyChecker(Checker):
    name = "error-vocabulary"
    targets = (
        "etcd_tpu/api/",
        "etcd_tpu/server/",
        "etcd_tpu/store/",
    )

    def __init__(self):
        self._vocab_cache: dict[str, tuple[set[str], set[int]]] = {}

    def check(self, relpath, tree, source, root=None, ctx=None):
        root = root or os.getcwd()
        if root not in self._vocab_cache:
            self._vocab_cache[root] = _load_vocab(root)
        names, values = self._vocab_cache[root]

        scope_of: dict[int, str] = {}
        for scope, fn in iter_functions(tree):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Raise):
                    scope_of.setdefault(id(sub), scope)

        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise):
                continue
            scope = scope_of.get(id(node), "")
            exc = node.exc
            if exc is None:
                continue  # bare re-raise
            if not isinstance(exc, ast.Call):
                # `raise resp.err` / `raise e` — variable re-raise;
                # but a bare TYPE (`raise ValueError`) checks like a
                # zero-arg construction
                leaf = dotted_name(exc).split(".")[-1]
                if leaf and leaf[:1].isupper() \
                        and (leaf.endswith("Error")
                             or leaf.endswith("Exception")) \
                        and leaf not in _ALLOWED:
                    findings.append(self._finding(
                        relpath, node, scope, leaf,
                        f"`raise {leaf}` is outside the error "
                        f"vocabulary and the internal allow-list"))
                continue
            leaf = dotted_name(exc.func).split(".")[-1]
            if not leaf:
                continue  # computed constructor — can't resolve
            if leaf in _VOCAB_CTORS:
                findings.extend(self._check_code(
                    relpath, node, scope, exc, names, values))
                continue
            if leaf in _ALLOWED:
                continue
            if leaf in ("Exception", "BaseException"):
                findings.append(self._finding(
                    relpath, node, scope, leaf,
                    "generic `Exception` raised on the "
                    "client-visible tier — use EtcdError or a typed "
                    "internal exception"))
                continue
            findings.append(self._finding(
                relpath, node, scope, leaf,
                f"`{leaf}` is not in the numeric error vocabulary "
                f"or the internal allow-list"))
        return findings

    def _check_code(self, relpath, node, scope, call, names,
                    values) -> list[Finding]:
        if not call.args:
            return [self._finding(
                relpath, node, scope, "missing-code",
                "vocabulary constructor called without an error "
                "code")]
        code = call.args[0]
        if isinstance(code, ast.Name):
            if code.id.startswith("ECODE_") and names \
                    and code.id not in names:
                return [self._finding(
                    relpath, node, scope, code.id,
                    f"`{code.id}` is not defined in "
                    f"utils/errors.py")]
            return []  # a variable code — resolved at runtime
        if isinstance(code, ast.Constant) \
                and isinstance(code.value, int):
            if values and code.value not in values:
                return [self._finding(
                    relpath, node, scope, str(code.value),
                    f"numeric code {code.value} is not in the "
                    f"vocabulary (utils/errors.py)")]
            return []
        if isinstance(code, (ast.Attribute, ast.Call,
                             ast.Subscript, ast.IfExp, ast.BinOp)):
            return []  # runtime-resolved code (e.g. e.error_code,
            #            d.get("errorCode", 300))
        return [self._finding(
            relpath, node, scope, "opaque-code",
            "error code expression cannot be resolved to the "
            "vocabulary")]

    def _finding(self, relpath, node, scope, detail,
                 message) -> Finding:
        return Finding(
            checker=self.name, path=relpath, line=node.lineno,
            rule="unknown-exception", scope=scope, message=message,
            detail=detail)
