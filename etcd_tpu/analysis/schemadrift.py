"""schema-drift: the declared layout, the marshaler, and the
unmarshaler must agree — and the schema is the only place layout
literals live.

wire/schema.py is the single source of truth for every frame layout
(PR 19).  Drift between it and the parser modules is the silent-
corruption failure class: a field written at one offset and read at
another, a section reordered on one side only, a struct format
re-declared locally and edited out of sync.  Three rules:

  * ``local-struct-literal`` / ``local-magic-literal`` — a wire
    module other than the schema declares a ``struct.Struct("...")``
    format string or a frame magic literal.  Layout constants must be
    imported from the schema so there is exactly one copy to edit.
  * ``section-drift`` — for every DGB2-style frame kind, the ordered
    ``_w_i32``/``_w_u8`` writes in ``marshal`` and the ordered
    ``_view_i32``/``_view_u8`` reads in ``unmarshal`` are extracted
    and compared against the schema's declared sections.  A section
    written but not read, read at a different position, or read with
    a different element width fails lint.
  * ``field-drift`` — for every gogoproto message, the tag bytes
    emitted by ``marshal`` and the ``fnum ==``/``_expect_wt`` dispatch
    arms in ``unmarshal`` are compared against the schema's declared
    (field number, wire type) pairs, both directions.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions
from .wiremodel import SCHEMA_RELPATH, WIRE_TARGETS, module_schema
from ..wire import schema as _schema

#: section element -> (writer helper, reader helper)
_ELEM_CALLS = {"i32": ("_w_i32", "_view_i32"),
               "u8": ("_w_u8", "_view_u8")}
_WRITERS = {w: e for e, (w, _r) in _ELEM_CALLS.items()}
_READERS = {r: e for e, (_w, r) in _ELEM_CALLS.items()}


def _magic_literals() -> tuple[set[bytes], set[int]]:
    bmagics: set[bytes] = set()
    imagics: set[int] = set()
    for f in _schema.FORMATS:
        if isinstance(f.magic, bytes) and f.magic:
            bmagics.add(f.magic)
        elif isinstance(f.magic, int):
            imagics.add(f.magic)
    return bmagics, imagics


def _arg_name(node: ast.AST) -> str:
    """Best-effort payload name of a section write argument:
    ``self.term`` -> term, ``n_ents`` -> n_ents,
    ``np.asarray(lens, ...)`` -> lens."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        for a in node.args:
            got = _arg_name(a)
            if got:
                return got
    return ""


def _top_level_calls(fn: ast.AST):
    """(statement, call) for each unconditional top-level statement
    of ``fn`` whose value is a helper call, in source order.  Only
    top-level statements count: the schema's ordered sections are
    mandatory, while flag-gated trailing sections (FLAG_PACKED's
    table) legitimately marshal under an ``if``."""
    for s in fn.body:
        value = getattr(s, "value", None)
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.Expr)) \
                and isinstance(value, ast.Call):
            yield s, value


def _ordered_calls(fn: ast.AST,
                   table: dict[str, str]) -> list[tuple[str, str]]:
    """[(elem, payload name)] for every unconditional helper call
    from ``table`` in ``fn``, in source order."""
    out = []
    for _s, n in _top_level_calls(fn):
        f = n.func
        last = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if last in table and len(n.args) >= 3:
            out.append((table[last], _arg_name(n.args[2])))
    return out


def _ordered_reads(fn: ast.AST) -> list[tuple[str, str]]:
    """[(elem, bound local name)] for every unconditional
    ``name, pos = _view_*(...)`` in ``fn``, in source order."""
    out = []
    for s, n in _top_level_calls(fn):
        if not isinstance(s, ast.Assign):
            continue
        f = n.func
        last = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if last not in _READERS:
            continue
        tgt = s.targets[0]
        if isinstance(tgt, ast.Tuple) and tgt.elts \
                and isinstance(tgt.elts[0], ast.Name):
            out.append((_READERS[last], tgt.elts[0].id))
    return out


class SchemaDriftChecker(Checker):
    name = "schema-drift"
    targets = WIRE_TARGETS

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        if relpath == SCHEMA_RELPATH:
            return []
        out: list[Finding] = []
        self._check_literals(relpath, tree, out)
        sch = module_schema(relpath)
        if sch is None:
            return out
        funcs = dict(iter_functions(tree))
        for kind in sch.kinds:
            self._check_sections(relpath, kind, funcs, out)
        for msg in sch.messages:
            self._check_fields(relpath, msg, funcs, out)
        return out

    # -- layout literals belong in the schema ---------------------------

    def _check_literals(self, relpath: str, tree: ast.AST,
                        out: list[Finding]) -> None:
        bmagics, imagics = _magic_literals()
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) \
                    and dotted_name(n.func).rsplit(".", 1)[-1] \
                    == "Struct" \
                    and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=n.lineno, rule="local-struct-literal",
                    scope="",
                    message=f"struct format "
                            f"{n.args[0].value!r} declared locally "
                            f"— import it from wire/schema.py "
                            f"(structs / header_struct) so there "
                            f"is one copy to edit",
                    detail=n.args[0].value))
            elif isinstance(n, ast.Constant) \
                    and ((isinstance(n.value, bytes)
                          and n.value in bmagics)
                         or (isinstance(n.value, int)
                             and not isinstance(n.value, bool)
                             and n.value in imagics)):
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=n.lineno, rule="local-magic-literal",
                    scope="",
                    message=f"frame magic {n.value!r} declared "
                            f"locally — import it from "
                            f"wire/schema.py",
                    detail=repr(n.value)))

    # -- DGB2-style ordered sections ------------------------------------

    def _check_sections(self, relpath: str, kind, funcs,
                        out: list[Finding]) -> None:
        expected = [(s.elem, s.name) for s in kind.sections
                    if s.elem in _ELEM_CALLS]
        if not expected:
            return
        wfn = funcs.get(kind.marshal) if kind.marshal else None
        rfn = funcs.get(kind.unmarshal) if kind.unmarshal else None
        if wfn is not None:
            writes = _ordered_calls(wfn, _WRITERS)
            if writes and writes != expected:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=wfn.lineno, rule="section-drift",
                    scope=kind.marshal,
                    message=f"{kind.marshal} writes {writes} but "
                            f"the schema declares {expected} for "
                            f"{kind.name} — reorder/fix one side "
                            f"or update the schema",
                    detail=f"{kind.name}:marshal"))
        if rfn is not None:
            exp_r = [(s.elem, s.read_name) for s in kind.sections
                     if s.elem in _ELEM_CALLS]
            reads = _ordered_reads(rfn)
            if reads and reads != exp_r:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=rfn.lineno, rule="section-drift",
                    scope=kind.unmarshal,
                    message=f"{kind.unmarshal} reads {reads} but "
                            f"the schema declares {exp_r} for "
                            f"{kind.name} — a reordered read "
                            f"silently swaps sections",
                    detail=f"{kind.name}:unmarshal"))

    # -- gogoproto field tags -------------------------------------------

    def _check_fields(self, relpath: str, msg, funcs,
                      out: list[Finding]) -> None:
        declared = {f.fnum: f.wt for f in msg.fields}
        wfn = funcs.get(f"{msg.cls}.marshal")
        rfn = funcs.get(f"{msg.cls}.unmarshal")
        if wfn is not None:
            written: dict[int, int] = {}
            for n in ast.walk(wfn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id in ("_tagged_varint",
                                          "_tagged_bytes") \
                        and len(n.args) >= 2 \
                        and isinstance(n.args[1], ast.Constant) \
                        and isinstance(n.args[1].value, int):
                    tag = n.args[1].value
                    written[tag >> 3] = tag & 7
            if written:
                self._diff(relpath, msg, wfn, "marshal", written,
                           declared, out)
        if rfn is not None:
            read: dict[int, int] = {}
            for n in ast.walk(rfn):
                if not isinstance(n, ast.If):
                    continue
                t = n.test
                if not (isinstance(t, ast.Compare)
                        and len(t.ops) == 1
                        and isinstance(t.ops[0], ast.Eq)):
                    continue
                sides = [t.left, t.comparators[0]]
                fnum = next((s.value for s in sides
                             if isinstance(s, ast.Constant)
                             and isinstance(s.value, int)), None)
                if fnum is None or not any(
                        isinstance(s, ast.Name)
                        and "num" in s.id for s in sides):
                    continue
                wt = next(
                    (c.args[2].value for s in n.body
                     for c in ast.walk(s)
                     if isinstance(c, ast.Call)
                     and isinstance(c.func, ast.Name)
                     and c.func.id == "_expect_wt"
                     and len(c.args) >= 3
                     and isinstance(c.args[2], ast.Constant)),
                    -1)
                read[fnum] = wt
            if read:
                self._diff(relpath, msg, rfn, "unmarshal", read,
                           declared, out)

    def _diff(self, relpath: str, msg, fn, side: str,
              actual: dict[int, int], declared: dict[int, int],
              out: list[Finding]) -> None:
        verb = "writes" if side == "marshal" else "reads"
        for fnum in sorted(actual.keys() | declared.keys()):
            if fnum not in declared:
                why = (f"{msg.cls}.{side} {verb} field {fnum} "
                       f"(wt {actual[fnum]}) not declared in the "
                       f"schema")
            elif fnum not in actual:
                why = (f"{msg.cls}.{side} never {verb} declared "
                       f"field {fnum} — "
                       f"{'silent data loss' if side == 'marshal' else 'the field is written but never read'}")
            elif actual[fnum] != declared[fnum]:
                why = (f"{msg.cls}.{side} {verb} field {fnum} as "
                       f"wire type {actual[fnum]}, schema declares "
                       f"{declared[fnum]}")
            else:
                continue
            out.append(Finding(
                checker=self.name, path=relpath, line=fn.lineno,
                rule="field-drift", scope=f"{msg.cls}.{side}",
                message=why, detail=f"{msg.cls}.f{fnum}:{side}"))
