"""bounded-queue: hot-path queues must declare a bound.

The PR-9 lesson, made a checker: an unbounded ``queue.Queue()`` or
``collections.deque()`` between a producer that can outrun its
consumer is a memory-exhaustion bug with a delay fuse — the watch
fanout's 1 KiB-per-watcher ``queue.Queue`` was replaced by the
slotted BoundedEventQueue precisely because "the queue grows until
the process dies" is not a policy.  On the server/store hot paths
every queue constructor must either pass an explicit bound
(``maxsize=``/``maxlen=``) or carry a baseline justification naming
the external bound (a pipeline-depth window, a capacity check in the
owning class, a drain-before-produce protocol).

Flagged shapes (string-resolvable constructors only):

- ``queue.Queue()`` / ``Queue()`` with no ``maxsize``, or a literal
  ``maxsize`` <= 0 (the stdlib's "0 means infinite" footgun);
- ``queue.SimpleQueue()`` — unbounded by construction;
- ``deque()`` / ``collections.deque(iterable)`` without ``maxlen``.

A non-literal bound (``maxsize=n``) is trusted: the policy decision
exists in code, which is what the rule is for.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map

_QUEUE_NAMES = {"Queue", "LifoQueue", "PriorityQueue"}


def _bound_arg(node: ast.Call, kw_name: str, pos: int):
    """The bound argument node, or None when absent."""
    for kw in node.keywords:
        if kw.arg == kw_name:
            return kw.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _literal_nonpositive(arg: ast.AST) -> bool:
    return isinstance(arg, ast.Constant) \
        and isinstance(arg.value, (int, float)) \
        and not isinstance(arg.value, bool) and arg.value <= 0


class BoundedQueueChecker(Checker):
    name = "bounded-queue"
    targets = ("etcd_tpu/server/", "etcd_tpu/store/")

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        owner = scope_map(tree)
        out: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            last = name.rsplit(".", 1)[-1]
            scope = owner.get(node, "")
            if last == "SimpleQueue":
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="unbounded-queue",
                    scope=scope,
                    message=f"{name}() is unbounded by construction "
                            f"— use a bounded queue on hot paths",
                    detail=last))
            elif last in _QUEUE_NAMES:
                bound = _bound_arg(node, "maxsize", 0)
                if bound is None or _literal_nonpositive(bound):
                    out.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="unbounded-queue",
                        scope=scope,
                        message=f"{name}() without a positive "
                                f"maxsize is unbounded (stdlib "
                                f"maxsize<=0 means infinite) — pass "
                                f"an explicit bound or justify the "
                                f"external one in the baseline",
                        detail=last))
            elif last == "deque":
                bound = _bound_arg(node, "maxlen", 1)
                if bound is None \
                        or (isinstance(bound, ast.Constant)
                            and bound.value is None):
                    out.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="unbounded-queue",
                        scope=scope,
                        message=f"{name}() without maxlen is "
                                f"unbounded — pass maxlen or justify "
                                f"the external bound in the baseline",
                        detail=last))
        return out
