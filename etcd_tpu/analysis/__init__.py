"""Repo-native static analysis (the `go vet` analog for this tree).

The reference leaned on ``go vet`` and the race detector; this package
is the same idea specialized to THIS codebase's three failure classes
that cost whole rounds and that the 6-minute suite cannot see:

- **tracer-purity** (purity.py): host syncs and impure calls inside
  code reachable from ``jax.jit``/``vmap``/``pallas_call`` roots —
  ``.item()``, ``int()/float()/bool()`` on traced values,
  ``np.*`` on traced values, Python ``if``/``while`` on traced names,
  wall-clock/random calls that would bake into a trace.
- **lock-discipline** (locks.py): the lock-acquisition graph across
  the threaded store/server tier — cycles (deadlock risk) and writes
  to attributes the rest of the class only touches under a lock.
- **durability-ordering** (durability.py): in the WAL and the
  snapshotter, every path from a write/rename/unlink to a return must
  pass through flush+fsync (acks only follow fsync — the contract
  torn-tail repair relies on).
- **error-vocabulary** (errorvocab.py): every ``raise`` on the
  client-visible tier resolves to the numeric vocabulary in
  utils/errors.py or an allow-listed internal type.
- **metrics-vocabulary** (metricsvocab.py): every obs-registry
  accessor call uses a string-literal metric name registered in
  obs/metrics.py's CATALOG — no ad-hoc metric keys (PR 2).
- **fault-vocabulary** (faultvocab.py): every fault-registry
  ``hit()`` call uses a string-literal failpoint name registered in
  utils/faults.py's FAULT_CATALOG — a typo'd failpoint would
  silently never fire (PR 10).
- **device-boundary** (boundary.py): ``np.asarray``/``np.array`` on
  a just-produced jitted result inside a per-round loop — the
  transfer-per-round tax behind the 24x restart regression (PR 3;
  the runtime half lives in obs/devledger.py).
- **static-shapes** (shapes.py): Python branching on a parameter's
  ``.shape`` inside a jit root whose project call sites (via the
  call graph) pass differently-shaped arrays — re-jit churn (PR 4).
- **seq-contiguity** (seqcontig.py): ``self.seq += 1`` allocation
  and the WAL-record construction that consumes it must stay
  adjacent — no yield/await/lock gap where another allocator can
  interleave (the out-of-order-seq restart class, PR 4).
- **timeout-bands** (timeouts.py): ``election >= m`` and
  ``heartbeat < election`` at every config surface — constructor
  call sites AND argparse flag defaults (PR 4).
- **bounded-queue** (boundedq.py): ``queue.Queue()``/``deque()``
  constructed without a bound on the server/store hot paths — the
  PR-9 BoundedEventQueue lesson as a rule; external bounds need a
  baseline justification (PR 12).
- **lock-order** (lockorder.py): the GLOBAL lock-acquisition graph
  (instance + module-level locks, held sets propagated across call
  edges via the shared concurrency model in concmodel.py) — cycles
  are potential cross-module deadlocks (PR 16).
- **blocking-under-lock** (blocking.py): fsync/socket/sleep/
  blocking-queue/subprocess/jit-dispatch operations reachable while
  a hot-path lock (world lock, hub mutex, server lock, frontdoor
  loop lock, ...) is held — the static form of the PR-6 stall class
  (PR 16).
- **thread-ownership** (ownership.py): ``# owner: <domain>``
  annotations + a registry of thread/process roots; attribute
  writes to a domain reached from a non-owner root (frontdoor
  per-conn state, shm-ring cursors, distpipe bookkeeping) are
  flagged (PR 16).
- **wire-bounds** (wirebounds.py): wire-derived lengths/counts in
  the five frame formats' parse scopes must pass a dominating
  raising length check or a schema plausibility cap
  (wire/schema.py ``check_bound``) before sizing a loop, a
  ``frombuffer`` view, or an allocation; the BOUNDS catalog is a
  closed vocabulary checked both directions (PR 19).
- **frame-totality** (frametotality.py): parse paths raise only the
  format's typed error — unguarded struct unpacks and untyped
  decode/json escapes are findings, and every schema-declared frame
  kind and flag bit must reach explicit handling plus a typed
  unknown-kind rejection (PR 19).
- **schema-drift** (schemadrift.py): marshal/unmarshal symmetry
  against the declarative schemas — locally re-declared struct/magic
  literals, reordered DGB2 sections, and gogoproto field tags that
  disagree with the declared (fnum, wiretype) pairs fail lint
  (PR 19).

Since PR 4 the suite is **whole-program**: ``callgraph.py`` builds a
project import/call graph once per run (cached on the engine's
``AnalysisContext`` beside the shared AST cache), the tracer-purity
taint walk follows tainted arguments across module boundaries, and
``scripts/lint --changed`` uses the reverse import closure to keep
restricted runs sound.

``scripts/lint`` runs the registry over the tree and gates on
``analysis_baseline.json`` (accepted legacy findings, each with a
one-line justification); ``tests/test_analysis.py`` wires the gate
into tier-1 and proves each checker fires on seeded violations.

The engine is stdlib-``ast`` only — no third-party deps, safe to run
anywhere the repo imports.
"""

from .blocking import BlockingUnderLockChecker
from .boundary import DeviceBoundaryChecker
from .boundedq import BoundedQueueChecker
from .callgraph import CallGraph
from .durability import DurabilityOrderingChecker
from .engine import (
    AnalysisContext,
    Baseline,
    Finding,
    load_baseline,
    prune_baseline,
    run_checkers,
    target_files,
)
from .errorvocab import ErrorVocabularyChecker
from .faultvocab import FaultVocabularyChecker
from .frametotality import FrameTotalityChecker
from .locks import LockDisciplineChecker
from .lockorder import LockOrderChecker
from .metricsvocab import MetricsVocabularyChecker
from .ownership import DOMAINS, Domain, OwnershipChecker
from .purity import TracerPurityChecker
from .schemadrift import SchemaDriftChecker
from .seqcontig import SeqContiguityChecker
from .shapes import StaticShapeChecker
from .timeouts import TimeoutBandChecker
from .wirebounds import WireBoundsChecker

#: the registry scripts/lint and tests/test_analysis.py run
ALL_CHECKERS = (
    TracerPurityChecker(),
    LockDisciplineChecker(),
    DurabilityOrderingChecker(),
    ErrorVocabularyChecker(),
    MetricsVocabularyChecker(),
    FaultVocabularyChecker(),
    DeviceBoundaryChecker(),
    StaticShapeChecker(),
    SeqContiguityChecker(),
    TimeoutBandChecker(),
    BoundedQueueChecker(),
    LockOrderChecker(),
    BlockingUnderLockChecker(),
    OwnershipChecker(),
    WireBoundsChecker(),
    FrameTotalityChecker(),
    SchemaDriftChecker(),
)

__all__ = [
    "ALL_CHECKERS",
    "AnalysisContext",
    "Baseline",
    "BlockingUnderLockChecker",
    "BoundedQueueChecker",
    "CallGraph",
    "DOMAINS",
    "DeviceBoundaryChecker",
    "Domain",
    "DurabilityOrderingChecker",
    "ErrorVocabularyChecker",
    "FaultVocabularyChecker",
    "Finding",
    "FrameTotalityChecker",
    "LockDisciplineChecker",
    "LockOrderChecker",
    "MetricsVocabularyChecker",
    "OwnershipChecker",
    "SchemaDriftChecker",
    "SeqContiguityChecker",
    "StaticShapeChecker",
    "TimeoutBandChecker",
    "TracerPurityChecker",
    "WireBoundsChecker",
    "load_baseline",
    "prune_baseline",
    "run_checkers",
    "target_files",
]
