"""durability-ordering: write → fsync before any return, and no
delete before the superseding write is fsynced.

The WAL/snapshot contract this tree's crash-recovery proofs lean on
(torn-tail repair, never-acked-tail drop) is "acks only follow
fsync".  Mechanically: in ``wal/wal.py`` and ``snap/snapshotter.py``,
every code path from a **mutation** — a file ``write``, an
``encoder.encode``, ``os.rename/remove/unlink/truncate/replace`` —
to a normal ``return`` (or falling off the function end) must pass
through a **sync** — ``.sync()``, ``os.fsync``, or a ``*fsync*``
helper (dir-fsync after unlink/rename included).  ``raise`` paths are
exempt: an exception is not an ack.

**Deletion ordering** (PR 6, the segment-GC / snapshot-purge rule):
an ``os.remove``/``os.unlink`` must never execute while an UNSYNCED
write/rename is pending on the path — the artifact that supersedes
the deleted one (the new snapshot, the repaired segment) must be
durable BEFORE the old one goes, or a crash between the two leaves
neither.  Reported as ``unsynced-delete`` at the remove site.
Removes themselves do not arm this rule for later removes (purging
N old snapshots needs one trailing dir fsync, not N interleaved
ones — snapshots are independent files; the WAL's GC adds its own
per-unlink dir fsync for seq contiguity, which this checker's
return rule separately requires).

Calls to other functions in the same module propagate: a call to a
function that can exit dirty marks the caller dirty (fixpoint), so a
buffered writer like ``save_entry`` is flagged at ITS boundary and
the composite ``save`` (which ends in ``sync()``) stays clean.
Intentionally-deferred writers (the encoder seam) are baselined with
justifications, not silenced in code.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions

_MUTATING_OS = {"rename", "remove", "unlink", "truncate", "replace",
                "ftruncate"}
#: the subset whose execution-while-write-dirty is the deletion-
#: ordering hazard (a superseded artifact removed before its
#: successor is durable)
_DELETING_OS = {"remove", "unlink"}

#: receivers whose ``.write`` is a digest update, not a file write
_NON_FILE_WRITE_RECV = ("crc", "digest", "hash")


def _last_component(node: ast.AST) -> str:
    return dotted_name(node).split(".")[-1]


class _PathState:
    __slots__ = ("dirty", "op", "wdirty", "wop")

    def __init__(self, dirty: bool = False, op: str = "",
                 wdirty: bool = False, wop: str = ""):
        self.dirty = dirty
        self.op = op  # the mutating call that set dirty (last wins)
        # write-dirty: an unsynced WRITE/rename (not a delete) is
        # pending — the state the unsynced-delete rule checks at
        # every remove/unlink site
        self.wdirty = wdirty
        self.wop = wop

    def copy(self) -> "_PathState":
        return _PathState(self.dirty, self.op, self.wdirty, self.wop)


class _FnEval:
    """Evaluate one function body: reports returns-while-dirty and
    whether the function can exit dirty (for caller propagation)."""

    def __init__(self, checker, relpath, scope, fn,
                 dirty_exit: dict[str, bool]):
        self.c = checker
        self.relpath = relpath
        self.scope = scope
        self.fn = fn
        self.dirty_exit = dirty_exit
        self.findings: list[Finding] = []
        self.exits_dirty = False

    def run(self) -> None:
        st = _PathState(False)
        out = self._block_st(self.fn.body, st)
        if out.dirty:
            # falling off the end returns None to the caller
            self.exits_dirty = True
            last = self.fn.body[-1]
            self.findings.append(self._finding(
                getattr(last, "lineno", self.fn.lineno), "end",
                out.op))

    def _finding(self, line: int, where: str, op: str) -> Finding:
        # detail carries the exit kind + the mutating op token, NOT
        # the line number: fingerprints must survive edits above the
        # site, while a future unrelated mutation (different op) in
        # an already-baselined function still gets a fresh
        # fingerprint instead of hiding under the old justification
        return Finding(
            checker=self.c.name, path=self.relpath, line=line,
            rule="unsynced-return", scope=self.scope,
            message=("path from `" + (op or "a write/rename")
                     + "` reaches "
                     + ("the function end" if where == "end"
                        else "a return")
                     + " without flush+fsync — an ack could precede "
                       "durability"),
            detail=f"{where}:{op}")

    def _delete_finding(self, line: int, del_op: str,
                        wop: str) -> Finding:
        return Finding(
            checker=self.c.name, path=self.relpath, line=line,
            rule="unsynced-delete", scope=self.scope,
            message=(f"`{del_op}` runs while `{wop or 'a write'}` "
                     "is not yet fsynced — the artifact superseding "
                     "the deleted one must be durable before the "
                     "old one goes (delete-after-fsync)"),
            detail=f"delete:{del_op}<-{wop}")

    # -- expression classification ---------------------------------------

    def _call_effect(self, node: ast.Call) -> str:
        """'sync' | 'write' | 'delete' | '' for one call node."""
        f = node.func
        name = dotted_name(f)
        leaf = name.split(".")[-1]
        if leaf == "fsync" or "fsync" in leaf or leaf == "sync":
            return "sync"
        if isinstance(f, ast.Attribute):
            recv = _last_component(f.value)
            if f.attr == "write" and not any(
                    k in recv for k in _NON_FILE_WRITE_RECV):
                return "write"
            if f.attr == "encode" and "encoder" in recv:
                return "write"
            if name.startswith("os.") and f.attr in _DELETING_OS:
                return "delete"
            if name.startswith("os.") and f.attr in _MUTATING_OS:
                return "write"
        # intra-module propagation by bare callee name: a callee
        # that can exit dirty counts as an unsynced write at the
        # call site (conservative — its pending bytes are whatever
        # it left unsynced)
        if self.dirty_exit.get(leaf):
            return "write"
        return ""

    def _scan_expr(self, node: ast.AST, st: _PathState) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                eff = self._call_effect(sub)
                if eff == "write":
                    st.dirty = True
                    st.wdirty = True
                    st.op = dotted_name(sub.func) or st.op
                    st.wop = st.op
                elif eff == "delete":
                    del_op = dotted_name(sub.func)
                    if st.wdirty:
                        # deletion ordering: the superseding write
                        # is not durable yet at this unlink
                        self.findings.append(self._delete_finding(
                            getattr(sub, "lineno", self.fn.lineno),
                            del_op, st.wop))
                        st.wdirty = False  # reported once per path
                    # a delete is still a mutation for the
                    # exit-synced rule (dir entry must be fsynced)
                    st.dirty = True
                    st.op = del_op or st.op
                elif eff == "sync":
                    st.dirty = False
                    st.wdirty = False

    # -- statements ------------------------------------------------------

    @staticmethod
    def _merge(st: _PathState, *outs: _PathState) -> None:
        st.dirty = any(o.dirty for o in outs)
        for o in outs:
            if o.dirty:
                st.op = o.op
                break
        st.wdirty = any(o.wdirty for o in outs)
        for o in outs:
            if o.wdirty:
                st.wop = o.wop
                break

    def _block_st(self, stmts, st_in: _PathState) -> _PathState:
        st = st_in.copy()
        for stmt in stmts:
            self._stmt(stmt, st)
        return st

    def _stmt(self, stmt, st: _PathState) -> None:
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, st)
            if st.dirty:
                self.findings.append(
                    self._finding(stmt.lineno, "return", st.op))
                self.exits_dirty = True
                st.dirty = False  # reported once per path
            st.wdirty = False
            return
        if isinstance(stmt, ast.Raise):
            st.dirty = False  # error propagation is not an ack
            st.wdirty = False
            return
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, st)
            a = self._block_st(stmt.body, st)
            b = self._block_st(stmt.orelse, st)
            self._merge(st, a, b)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._scan_expr(
                stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                else stmt.test, st)
            entry = st.copy()
            body = self._block_st(stmt.body, entry)
            # second-iteration check: re-running the body with the
            # first pass's exit state catches a loop whose delete
            # executes under dirt its OWN previous iteration left
            # (e.g. remove-without-sync per segment)
            self._block_st(stmt.body, body)
            after = _PathState()
            self._merge(after, entry, body)
            els = self._block_st(stmt.orelse, after)
            self._merge(st, entry, body, els)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, st)
            out = self._block_st(stmt.body, st)
            st.dirty, st.op = out.dirty, out.op
            st.wdirty, st.wop = out.wdirty, out.wop
            return
        if isinstance(stmt, ast.Try):
            body = self._block_st(stmt.body, st)
            outs = [body]
            for h in stmt.handlers:
                pre = _PathState()
                self._merge(pre, st, body)
                outs.append(self._block_st(h.body, pre))
            els = self._block_st(stmt.orelse, body)
            merged = _PathState()
            self._merge(merged, *outs, els)
            if stmt.finalbody:
                merged = self._block_st(stmt.finalbody, merged)
            st.dirty, st.op = merged.dirty, merged.op
            st.wdirty, st.wop = merged.wdirty, merged.wop
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs evaluated separately
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, st)


class DurabilityOrderingChecker(Checker):
    name = "durability-ordering"
    targets = (
        "etcd_tpu/wal/wal.py",
        "etcd_tpu/snap/snapshotter.py",
    )

    def check(self, relpath, tree, source, root=None, ctx=None):
        fns = list(iter_functions(tree))
        # fixpoint: which functions can exit dirty (by bare name —
        # good enough within one module)
        dirty_exit: dict[str, bool] = {}
        for _ in range(4):
            changed = False
            for scope, fn in fns:
                ev = _FnEval(self, relpath, scope, fn, dirty_exit)
                ev.run()
                prev = dirty_exit.get(fn.name, False)
                if ev.exits_dirty != prev:
                    dirty_exit[fn.name] = ev.exits_dirty
                    changed = True
            if not changed:
                break
        findings: list[Finding] = []
        for scope, fn in fns:
            ev = _FnEval(self, relpath, scope, fn, dirty_exit)
            ev.run()
            findings.extend(ev.findings)
        # de-dup (fixpoint pass may emit duplicates)
        seen = set()
        out = []
        for f in findings:
            key = (f.fingerprint, f.line)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
