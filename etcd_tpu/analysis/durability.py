"""durability-ordering: write → fsync before any return.

The WAL/snapshot contract this tree's crash-recovery proofs lean on
(torn-tail repair, never-acked-tail drop) is "acks only follow
fsync".  Mechanically: in ``wal/wal.py`` and ``snap/snapshotter.py``,
every code path from a **mutation** — a file ``write``, an
``encoder.encode``, ``os.rename/remove/unlink/truncate/replace`` —
to a normal ``return`` (or falling off the function end) must pass
through a **sync** — ``.sync()``, ``os.fsync``, or a ``*fsync*``
helper (dir-fsync after unlink/rename included).  ``raise`` paths are
exempt: an exception is not an ack.

Calls to other functions in the same module propagate: a call to a
function that can exit dirty marks the caller dirty (fixpoint), so a
buffered writer like ``save_entry`` is flagged at ITS boundary and
the composite ``save`` (which ends in ``sync()``) stays clean.
Intentionally-deferred writers (the encoder seam) are baselined with
justifications, not silenced in code.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions

_MUTATING_OS = {"rename", "remove", "unlink", "truncate", "replace",
                "ftruncate"}

#: receivers whose ``.write`` is a digest update, not a file write
_NON_FILE_WRITE_RECV = ("crc", "digest", "hash")


def _last_component(node: ast.AST) -> str:
    return dotted_name(node).split(".")[-1]


class _PathState:
    __slots__ = ("dirty", "op")

    def __init__(self, dirty: bool = False, op: str = ""):
        self.dirty = dirty
        self.op = op  # the mutating call that set dirty (last wins)


class _FnEval:
    """Evaluate one function body: reports returns-while-dirty and
    whether the function can exit dirty (for caller propagation)."""

    def __init__(self, checker, relpath, scope, fn,
                 dirty_exit: dict[str, bool]):
        self.c = checker
        self.relpath = relpath
        self.scope = scope
        self.fn = fn
        self.dirty_exit = dirty_exit
        self.findings: list[Finding] = []
        self.exits_dirty = False

    def run(self) -> None:
        st = _PathState(False)
        out = self._block_st(self.fn.body, st)
        if out.dirty:
            # falling off the end returns None to the caller
            self.exits_dirty = True
            last = self.fn.body[-1]
            self.findings.append(self._finding(
                getattr(last, "lineno", self.fn.lineno), "end",
                out.op))

    def _finding(self, line: int, where: str, op: str) -> Finding:
        # detail carries the exit kind + the mutating op token, NOT
        # the line number: fingerprints must survive edits above the
        # site, while a future unrelated mutation (different op) in
        # an already-baselined function still gets a fresh
        # fingerprint instead of hiding under the old justification
        return Finding(
            checker=self.c.name, path=self.relpath, line=line,
            rule="unsynced-return", scope=self.scope,
            message=("path from `" + (op or "a write/rename")
                     + "` reaches "
                     + ("the function end" if where == "end"
                        else "a return")
                     + " without flush+fsync — an ack could precede "
                       "durability"),
            detail=f"{where}:{op}")

    # -- expression classification ---------------------------------------

    def _call_effect(self, node: ast.Call) -> str:
        """'sync' | 'dirty' | '' for one call node."""
        f = node.func
        name = dotted_name(f)
        leaf = name.split(".")[-1]
        if leaf == "fsync" or "fsync" in leaf or leaf == "sync":
            return "sync"
        if isinstance(f, ast.Attribute):
            recv = _last_component(f.value)
            if f.attr == "write" and not any(
                    k in recv for k in _NON_FILE_WRITE_RECV):
                return "dirty"
            if f.attr == "encode" and "encoder" in recv:
                return "dirty"
            if name.startswith("os.") and f.attr in _MUTATING_OS:
                return "dirty"
        # intra-module propagation by bare callee name
        if self.dirty_exit.get(leaf):
            return "dirty"
        return ""

    def _scan_expr(self, node: ast.AST, st: _PathState) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                eff = self._call_effect(sub)
                if eff == "dirty":
                    st.dirty = True
                    st.op = dotted_name(sub.func) or st.op
                elif eff == "sync":
                    st.dirty = False

    # -- statements ------------------------------------------------------

    @staticmethod
    def _merge(st: _PathState, *outs: _PathState) -> None:
        st.dirty = any(o.dirty for o in outs)
        for o in outs:
            if o.dirty:
                st.op = o.op
                break

    def _block_st(self, stmts, st_in: _PathState) -> _PathState:
        st = _PathState(st_in.dirty, st_in.op)
        for stmt in stmts:
            self._stmt(stmt, st)
        return st

    def _stmt(self, stmt, st: _PathState) -> None:
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, st)
            if st.dirty:
                self.findings.append(
                    self._finding(stmt.lineno, "return", st.op))
                self.exits_dirty = True
                st.dirty = False  # reported once per path
            return
        if isinstance(stmt, ast.Raise):
            st.dirty = False  # error propagation is not an ack
            return
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, st)
            a = self._block_st(stmt.body, st)
            b = self._block_st(stmt.orelse, st)
            self._merge(st, a, b)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._scan_expr(
                stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                else stmt.test, st)
            entry = _PathState(st.dirty, st.op)
            body = self._block_st(stmt.body, entry)
            after = _PathState(entry.dirty or body.dirty,
                               body.op if body.dirty else entry.op)
            els = self._block_st(stmt.orelse, after)
            self._merge(st, entry, body, els)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, st)
            out = self._block_st(stmt.body, st)
            st.dirty, st.op = out.dirty, out.op
            return
        if isinstance(stmt, ast.Try):
            body = self._block_st(stmt.body, st)
            outs = [body]
            for h in stmt.handlers:
                pre = _PathState(st.dirty or body.dirty,
                                 body.op if body.dirty else st.op)
                outs.append(self._block_st(h.body, pre))
            els = self._block_st(stmt.orelse, body)
            merged = _PathState()
            self._merge(merged, *outs, els)
            if stmt.finalbody:
                merged = self._block_st(stmt.finalbody, merged)
            st.dirty, st.op = merged.dirty, merged.op
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs evaluated separately
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, st)


class DurabilityOrderingChecker(Checker):
    name = "durability-ordering"
    targets = (
        "etcd_tpu/wal/wal.py",
        "etcd_tpu/snap/snapshotter.py",
    )

    def check(self, relpath, tree, source, root=None, ctx=None):
        fns = list(iter_functions(tree))
        # fixpoint: which functions can exit dirty (by bare name —
        # good enough within one module)
        dirty_exit: dict[str, bool] = {}
        for _ in range(4):
            changed = False
            for scope, fn in fns:
                ev = _FnEval(self, relpath, scope, fn, dirty_exit)
                ev.run()
                prev = dirty_exit.get(fn.name, False)
                if ev.exits_dirty != prev:
                    dirty_exit[fn.name] = ev.exits_dirty
                    changed = True
            if not changed:
                break
        findings: list[Finding] = []
        for scope, fn in fns:
            ev = _FnEval(self, relpath, scope, fn, dirty_exit)
            ev.run()
            findings.extend(ev.findings)
        # de-dup (fixpoint pass may emit duplicates)
        seen = set()
        out = []
        for f in findings:
            key = (f.fingerprint, f.line)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
