"""fault-vocabulary: failpoint names must be in the closed catalog.

The fault registry already raises ``FaultSpecError`` at configure
time for a spec naming an unknown failpoint — but a SEAM calling
``_faults.hit("wal.fsnyc")`` (typo) would silently never fire,
because nothing validates the call-site side at runtime (an unknown
point simply matches no rules).  This checker moves that to lint
time, mirroring metrics-vocabulary: every ``<faults-ish>.hit("...")``
call with a string-literal name must name a catalog entry
(utils/faults.py ``FAULT_CATALOG``), and a *dynamic* name is flagged
too — it defeats both this check and the README's failpoint table.

"Faults-ish" receivers: the final attribute/name segment is one of
``faults`` / ``_faults`` / ``FAULTS`` (the repo's binding
conventions: ``from ..utils import faults as _faults`` at seams,
``FAULTS.hit`` on the registry object).
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map

_RECEIVERS = {"faults", "_faults", "FAULTS"}


class FaultVocabularyChecker(Checker):
    name = "fault-vocabulary"
    targets = ("etcd_tpu/", "scripts/", "bench.py")

    def _catalog(self) -> set[str] | None:
        try:
            from ..utils.faults import FAULT_CATALOG

            return set(FAULT_CATALOG)
        except Exception:  # pragma: no cover - bootstrap order
            return None

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        if relpath == "etcd_tpu/utils/faults.py":
            return []  # the catalog itself
        catalog = self._catalog()
        if catalog is None:  # pragma: no cover
            return []
        owner = scope_map(tree)
        out: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr != "hit":
                continue
            recv = dotted_name(func.value)
            recv_last = recv.rsplit(".", 1)[-1] if recv else ""
            if recv_last not in _RECEIVERS:
                continue
            scope = owner.get(node, "")
            literal = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                literal = node.args[0].value
            if literal is None:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="dynamic-fault-name",
                    scope=scope,
                    message=f"{recv}.hit(<non-literal>) — failpoint "
                            f"names must be string literals from "
                            f"utils/faults.py's FAULT_CATALOG",
                    detail=f"{recv_last}.hit"))
            elif literal not in catalog:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="unregistered-fault",
                    scope=scope,
                    message=f"failpoint {literal!r} is not "
                            f"registered in utils/faults.py's "
                            f"FAULT_CATALOG — it would silently "
                            f"never fire",
                    detail=literal))
        return out
