"""Global lock-order checker (PR 16 tentpole, part 1).

The class-local lock-discipline checker (locks.py) orders locks
*within* one class; the deadlocks the role split can actually
manufacture are cross-module: peerlink stripe conds vs. the
DistServer lock, the store world lock vs. the hub mutex, the
frontdoor loop lock vs. worker-side state.  This checker builds the
ONE global lock-acquisition graph:

- nodes are lock identities — ``Class.attr`` for instance locks,
  ``path.py:var`` for module-level locks (from the shared
  concurrency model);
- an edge A → B means "somewhere, B is acquired while A is held",
  where "held" combines the lexical ``with`` nesting, the
  must-held-at-entry set propagated across call edges (the
  cross-module form of the "call with lock held" convention), and
  the transitive acquisitions of every callee reached under A;
- a cycle is a potential deadlock: two threads walking the cycle
  from different entry edges can block each other forever.

Re-entrant self-edges (RLock re-acquisition) are not edges.
Suppress a deliberate ordering with ``# lint: ok(lock-order)`` on
the acquisition (or call) line that closes the cycle, or via the
baseline with a written justification.
"""

from __future__ import annotations

import ast

from .concmodel import concurrency_model
from .engine import AnalysisContext, Checker, Finding


class LockOrderChecker(Checker):
    name = "lock-order"
    targets = ("etcd_tpu/",)

    def __init__(self):
        self._cache: dict[str, dict[str, list[Finding]]] = {}

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None,
              ctx: AnalysisContext | None = None) -> list[Finding]:
        if root is None or ctx is None:
            return []
        by_file = self._cache.get(root)
        if by_file is None:
            by_file = self._analyze(root, ctx)
            self._cache[root] = by_file
        return list(by_file.get(relpath, ()))

    # ------------------------------------------------------------------

    def _analyze(self, root: str,
                 ctx: AnalysisContext) -> dict[str, list[Finding]]:
        model = concurrency_model(root, ctx)
        entry = model.entry_held_intersection()
        acq = model.transitive_acquires()

        # edge (a, b) -> representative site (path, scope, line, why)
        edges: dict[tuple[str, str], tuple] = {}

        def add_edge(a: str, b: str, fi, line: int,
                     why: str) -> None:
            if a == b:
                return  # RLock re-entry
            edges.setdefault(
                (a, b), (fi.relpath, fi.scope, line, why))

        for key, fi in model.functions.items():
            if fi.scope.split(".")[-1] == "__init__":
                continue  # construction is single-threaded
            base = entry.get(key, frozenset())
            for lock, held, line in fi.acquires:
                for h in frozenset(held) | base:
                    add_edge(h, lock, fi, line,
                             f"acquires {lock}")
            for callee, held, line in fi.edges:
                outer = frozenset(held) | base
                if not outer:
                    continue
                for t in acq.get(callee, ()):
                    cs = callee[1]
                    for h in outer:
                        add_edge(h, t, fi, line,
                                 f"call into {cs} acquires {t}")

        graph: dict[str, set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())

        by_file: dict[str, list[Finding]] = {}
        for cycle in self._cycles(graph):
            # anchor the finding at the first edge's site; the
            # detail is the rotated lock chain, so the fingerprint
            # survives edits anywhere along the cycle
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            path, scope, line, why = edges[pairs[0]]
            chain = " -> ".join(cycle + [cycle[0]])
            sites = "; ".join(
                f"{edges[p][0]}:{edges[p][2]} ({edges[p][3]})"
                for p in pairs)
            by_file.setdefault(path, []).append(Finding(
                checker=self.name, path=path, line=line,
                rule="lock-cycle", scope=scope, detail=chain,
                message=(f"potential deadlock: lock-order cycle "
                         f"{chain} [{sites}]")))
        return by_file

    @staticmethod
    def _cycles(graph: dict[str, set[str]]) -> list[list[str]]:
        """Enumerate unique simple cycles (each reported once, from
        its lexicographically-least node; path length capped)."""
        out: list[list[str]] = []
        seen: set[frozenset] = set()

        def dfs(start: str, node: str,
                path: list[str]) -> None:
            if len(path) > 6:
                return
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen and path[0] == min(path):
                        seen.add(key)
                        out.append(list(path))
                elif nxt not in path and nxt > start:
                    dfs(start, nxt, path + [nxt])

        for start in sorted(graph):
            dfs(start, start, [start])
        return out
