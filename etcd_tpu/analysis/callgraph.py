"""Whole-program layer: project module index, import edges, and the
call graph the cross-module checkers query.

One instance per analysis run (it lives on the engine's
:class:`~.engine.AnalysisContext`, sharing its AST cache), built
lazily the first time a checker asks a cross-module question:

- **import resolution** — ``import a.b.c [as z]``, ``from X import y
  [as z]`` (absolute and relative), ``from X import *``, and
  re-exported names (``__init__.py`` doing ``from .wal import WAL``)
  all resolve to the defining module file under the repo root.
- **function resolution** — :meth:`CallGraph.resolve_call` maps a
  dotted call name in a module's context to the ``(relpath, scope,
  ast-node)`` definitions it can reach, following re-export chains.
- **call sites** — :meth:`CallGraph.call_sites_of` inverts that: for
  one definition, every project call expression that resolves to it
  (the static-shapes checker reads argument shapes off these).
- **reverse dependents** — :meth:`CallGraph.reverse_dependents`
  closes a changed-file set over reverse import edges, so a
  restricted ``scripts/lint --changed`` run still sees every module
  whose cross-module findings could move.

Only project files participate (``etcd_tpu/``, ``scripts/*.py``,
top-level ``*.py``); stdlib/third-party names simply fail to resolve,
which every caller treats as "not ours".
"""

from __future__ import annotations

import ast
import os
import threading

from .engine import dotted_name, iter_functions, scope_map

#: directories (and top-level files) that form the project for
#: whole-program purposes
_PROJECT_DIRS = ("etcd_tpu", "scripts")


def project_files(root: str) -> list[str]:
    """Repo-relative posix paths of every project ``*.py`` file."""
    out: list[str] = []
    for d in _PROJECT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirs, files in os.walk(base):
            dirs[:] = [x for x in dirs if x != "__pycache__"]
            for fn in files:
                if fn.endswith(".py"):
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    try:
        for fn in os.listdir(root):
            if fn.endswith(".py") \
                    and os.path.isfile(os.path.join(root, fn)):
                out.append(fn)
    except OSError:
        pass
    return sorted(set(out))


class ModuleInfo:
    """One parsed project module: its functions plus raw import
    records (resolved lazily by the owning :class:`CallGraph`)."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.tree = tree
        #: scope ("Class.method" / "fn") -> def node
        self.functions: dict[str, ast.AST] = {}
        #: bare def name -> [(scope, node)]
        self.by_name: dict[str, list] = {}
        for scope, node in iter_functions(tree):
            self.functions[scope] = node
            self.by_name.setdefault(node.name, []).append(
                (scope, node))
        #: ("from", level, module-or-None, [(name, asname)]) |
        #: ("import", "a.b.c", asname-or-None)
        self.import_records: list[tuple] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                self.import_records.append(
                    ("from", node.level, node.module,
                     [(a.name, a.asname) for a in node.names]))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.import_records.append(
                        ("import", a.name, a.asname))
        # filled by CallGraph._bind():
        #: local name -> (module relpath, remote name | None);
        #: remote None = the name IS a module alias
        self.imports: dict[str, tuple[str, str | None]] = {}
        #: dotted prefix ("a.b.c") -> module relpath, for plain
        #: ``import a.b.c`` attribute-chain calls
        self.dotted_imports: dict[str, str] = {}
        #: modules star-imported into this namespace
        self.star_imports: list[str] = []
        #: every project module this one imports (reverse-dep edges)
        self.imported_modules: set[str] = set()


class CallGraph:
    """Project-wide import/function index (see module docstring)."""

    def __init__(self, root: str, parse):
        """``parse(relpath) -> (tree, source)`` is the engine's cached
        AST accessor — the graph never re-reads a file the run already
        parsed."""
        self.root = root
        self._parse = parse
        self.files = project_files(root)
        self._fileset = set(self.files)
        self._modules: dict[str, ModuleInfo | None] = {}
        self._sites: dict[tuple[str, str], list] | None = None
        self._rev: dict[str, set[str]] | None = None
        self._entry_points: list[tuple[str, str]] | None = None
        # the checker fan-out in run_checkers shares one graph
        # across worker threads; lazy index builds are guarded
        self._build_lock = threading.Lock()

    # -- module access ----------------------------------------------------

    def module(self, relpath: str) -> ModuleInfo | None:
        mi = self._modules.get(relpath, False)
        if mi is not False:
            return mi
        try:
            tree, _source = self._parse(relpath)
            mi = ModuleInfo(relpath, tree)
            self._bind(mi)
        except (OSError, SyntaxError):
            mi = None
        self._modules[relpath] = mi
        return mi

    def resolve_module(self, parts: list[str]) -> str | None:
        """Module-name parts -> project relpath (file or package
        ``__init__.py``), None when it isn't ours."""
        if not parts:
            return None
        for cand in ("/".join(parts) + ".py",
                     "/".join(parts) + "/__init__.py"):
            if cand in self._fileset:
                return cand
        return None

    def _bind(self, mi: ModuleInfo) -> None:
        pkg = mi.relpath.split("/")[:-1]
        for rec in mi.import_records:
            if rec[0] == "import":
                _kind, dotted, asname = rec
                key = self.resolve_module(dotted.split("."))
                if key is None:
                    continue
                mi.imported_modules.add(key)
                if asname:
                    mi.imports[asname] = (key, None)
                else:
                    mi.dotted_imports[dotted] = key
                continue
            _kind, level, module, names = rec
            if level:
                # relative: level 1 = this package, 2 = parent, ...
                if level - 1 > len(pkg):
                    continue
                base = pkg[:len(pkg) - (level - 1)]
            else:
                base = []
            base = base + (module.split(".") if module else [])
            key = self.resolve_module(base)
            if key is None:
                continue
            mi.imported_modules.add(key)
            for name, asname in names:
                if name == "*":
                    mi.star_imports.append(key)
                    continue
                local = asname or name
                subkey = self.resolve_module(base + [name])
                if subkey is not None:
                    # ``from pkg import submodule [as z]``
                    mi.imported_modules.add(subkey)
                    mi.imports[local] = (subkey, None)
                else:
                    mi.imports[local] = (key, name)

    # -- function resolution ----------------------------------------------

    def resolve_function(self, modkey: str, fname: str,
                         _seen: set | None = None) -> list:
        """``(relpath, scope, node)`` definitions of ``fname`` in
        module ``modkey``, following re-export chains (``__init__.py``
        doing ``from .wal import f``) and star imports."""
        seen = _seen if _seen is not None else set()
        if (modkey, fname) in seen:
            return []
        seen.add((modkey, fname))
        mi = self.module(modkey)
        if mi is None:
            return []
        if fname in mi.by_name:
            return [(modkey, scope, node)
                    for scope, node in mi.by_name[fname]]
        hop = mi.imports.get(fname)
        if hop is not None:
            key, remote = hop
            if remote is not None:
                return self.resolve_function(key, remote, seen)
            return []  # a module alias is not a function
        out: list = []
        for key in mi.star_imports:
            out.extend(self.resolve_function(key, fname, seen))
        return out

    def resolve_call(self, relpath: str, name: str) -> list:
        """Definitions a call spelled ``name`` inside ``relpath`` can
        reach: local defs, ``from X import y as z`` names, module
        aliases (``import a.b as m; m.f()``), dotted module imports
        (``import a.b; a.b.f()``), star imports."""
        mi = self.module(relpath)
        if mi is None or not name:
            return []
        parts = name.split(".")
        if parts[0] in ("self", "cls"):
            return []
        if len(parts) == 1:
            return self.resolve_function(relpath, name)
        # module-alias attribute: ``m.f()``
        hop = mi.imports.get(parts[0])
        if hop is not None and hop[1] is None and len(parts) == 2:
            return self.resolve_function(hop[0], parts[1])
        # plain ``import a.b.c`` + ``a.b.c.f()``: everything before
        # the final attribute must be the imported module path
        key = mi.dotted_imports.get(".".join(parts[:-1]))
        if key is not None:
            return self.resolve_function(key, parts[-1])
        return []

    # -- call sites --------------------------------------------------------

    def call_sites_of(self, relpath: str, scope: str) -> list:
        """Every project call expression resolving to the definition
        at ``(relpath, scope)``: ``[(caller_relpath, caller_scope,
        ast.Call)]``."""
        with self._build_lock:
            if self._sites is None:
                self._build_sites()
        return self._sites.get((relpath, scope), [])

    def _build_sites(self) -> None:
        self._sites = {}
        for rel in self.files:
            mi = self.module(rel)
            if mi is None:
                continue
            owner = scope_map(mi.tree)
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if not fname:
                    continue
                for tkey, tscope, _tnode in \
                        self.resolve_call(rel, fname):
                    self._sites.setdefault(
                        (tkey, tscope), []).append(
                        (rel, owner.get(node, ""), node))

    # -- thread entry points ----------------------------------------------

    def thread_entry_points(self) -> list[tuple[str, str]]:
        """Unique ``(relpath, scope)`` definitions used as thread or
        process targets anywhere in the project —
        ``threading.Thread(target=f)``,
        ``multiprocessing.Process(target=self._run)``, bare
        ``Thread(target=...)``.  Each is the root of a NEW execution
        context: the ownership checker walks the call graph from
        these (plus the registered role mains), and held-lock
        propagation must NOT cross into them."""
        with self._build_lock:
            if self._entry_points is None:
                self._entry_points = self._find_entry_points()
        return list(self._entry_points)

    def _find_entry_points(self) -> list[tuple[str, str]]:
        out: set[tuple[str, str]] = set()
        for rel in self.files:
            mi = self.module(rel)
            if mi is None:
                continue
            owner = scope_map(mi.tree)
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                last = fname.split(".")[-1] if fname else ""
                if last not in ("Thread", "Process"):
                    continue
                head = fname.split(".")[0]
                if "." in fname and head not in (
                        "threading", "multiprocessing", "mp"):
                    continue
                target = next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "target"), None)
                if target is None:
                    continue
                key = self._resolve_spawn_target(
                    mi, owner.get(node, ""), target)
                if key is not None:
                    out.add(key)
        return sorted(out)

    def _resolve_spawn_target(self, mi: ModuleInfo,
                              spawn_scope: str,
                              target: ast.AST
                              ) -> tuple[str, str] | None:
        tname = dotted_name(target)
        if not tname:
            return None
        parts = tname.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            # method target: nearest enclosing scope prefix owning
            # a def of that name ("FrontDoor.start" -> "FrontDoor._run")
            probe = spawn_scope
            while "." in probe:
                probe = probe.rsplit(".", 1)[0]
                cand = f"{probe}.{parts[1]}"
                if cand in mi.functions:
                    return (mi.relpath, cand)
            return None
        # plain function / imported name
        for rel, scope, _node in self.resolve_call(
                mi.relpath, tname):
            return (rel, scope)
        # nested def in an enclosing function scope
        probe = spawn_scope
        while probe:
            cand = f"{probe}.{tname}"
            if cand in mi.functions:
                return (mi.relpath, cand)
            probe = probe.rsplit(".", 1)[0] if "." in probe else ""
        if tname in mi.functions:
            return (mi.relpath, tname)
        return None

    # -- import closures ---------------------------------------------------

    def import_closure(self, relpaths: set[str]) -> set[str]:
        """Transitive closure of "is imported by one of ``relpaths``"
        (the inputs themselves excluded).  ``--changed`` needs this
        FORWARD direction too: a new call site in a changed caller
        can create a finding in the jit-root module it imports
        (static-shapes flags the callee's file)."""
        out: set[str] = set()
        frontier = list(relpaths)
        while frontier:
            mi = self.module(frontier.pop())
            if mi is None:
                continue
            for dep in mi.imported_modules:
                if dep not in out and dep not in relpaths:
                    out.add(dep)
                    frontier.append(dep)
        return out

    # -- reverse import dependents ----------------------------------------

    def reverse_dependents(self, relpaths: set[str]) -> set[str]:
        """Transitive closure of "imports one of ``relpaths``" over
        the project (the changed files themselves excluded)."""
        with self._build_lock:
            if self._rev is None:
                rev: dict[str, set[str]] = {}
                for rel in self.files:
                    mi = self.module(rel)
                    if mi is None:
                        continue
                    for dep in mi.imported_modules:
                        rev.setdefault(dep, set()).add(rel)
                self._rev = rev
        out: set[str] = set()
        frontier = list(relpaths)
        while frontier:
            cur = frontier.pop()
            for importer in self._rev.get(cur, ()):
                if importer not in out and importer not in relpaths:
                    out.add(importer)
                    frontier.append(importer)
        return out
