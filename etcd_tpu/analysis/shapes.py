"""static-shapes: shape-dependent branching under divergent callers.

``@jax.jit`` caches one executable per *static signature*: argument
shapes are baked into the trace.  A Python branch on ``x.shape`` (or
``x.ndim``/``x.size``/``len(x)``) inside a jit root is therefore
legal — the purity checker de-taints those reads — but it turns every
NEW caller shape into a full re-trace + re-compile.  With tens of
thousands of co-hosted groups batched through a handful of kernels,
one shape-churning call site is a compile storm (PALLAS_NOTES'
re-jit-churn class).

This checker joins both halves statically, which needs the
whole-program call graph:

- **roots**: functions under a jit decoration (``@jax.jit``,
  ``functools.partial(jax.jit, ...)``) containing a Python
  ``if``/``while`` whose test reads the shape of a *non-static*
  parameter;
- **call sites**: every project call expression resolving to that
  root (``callgraph.call_sites_of`` — same module, ``from X import
  y`` edges, re-exports).  The argument feeding the shape-branched
  parameter is reduced to a static **shape token** when the call
  passes a literal-shaped constructor (``jnp.zeros((4, 8))``,
  ``np.ones(n_CONST)``, ``jnp.arange(16)``, ``jnp.array([...])``).

Rule ``shape-branch`` fires when two call sites prove **different**
tokens: the branch re-specializes per caller.  A single observed
shape, or call sites whose shapes the checker cannot prove, stay
quiet — runtime-shaped args are the norm and flagging them would be
noise.  Fix patterns: pad to one shape at the boundary, split the
root per shape family, or hoist the varying dimension into
``static_argnames`` so the specialization is at least declared.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions
from .purity import _decorator_root

#: shape reads that are static at trace time but specialize the jit
#: cache per caller shape
_SHAPE_ATTRS = {"shape", "ndim", "size"}

#: array constructors whose first argument IS the shape
_SHAPE_CTORS = {"zeros", "ones", "empty", "full"}


def _const_tuple(node: ast.AST) -> tuple | None:
    """Constant int / tuple-of-constant-ints -> shape tuple."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def shape_token(node: ast.AST) -> str | None:
    """A stable token for the static shape of an argument
    expression, or None when it cannot be proven."""
    if not isinstance(node, ast.Call):
        return None
    leaf = dotted_name(node.func).split(".")[-1]
    if leaf in _SHAPE_CTORS:
        shp = None
        if node.args:
            shp = _const_tuple(node.args[0])
        for kw in node.keywords:
            if kw.arg == "shape":
                shp = _const_tuple(kw.value)
        return str(shp) if shp is not None else None
    if leaf == "arange":
        if len(node.args) == 1:
            shp = _const_tuple(node.args[0])
            return str(shp) if shp is not None else None
        return None
    if leaf in ("array", "asarray") and node.args:
        arg = node.args[0]
        if isinstance(arg, (ast.List, ast.Tuple)):
            shp = _const_tuple(arg)
            if shp is not None:  # flat literal vector
                return str((len(arg.elts),))
        return None
    return None


def _param_names(fn) -> list[str]:
    args = fn.args
    return [a.arg for a in (args.posonlyargs + args.args)]


def _shape_branch_params(fn, statics) -> list[tuple[str, ast.AST]]:
    """(param, test-node) for every if/while test reading the shape
    of a non-static parameter of ``fn``."""
    params = {p for p in _param_names(fn)
              if p not in statics and p not in ("self", "cls")}
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        hit = None
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in _SHAPE_ATTRS \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in params:
                hit = sub.value.id
            elif isinstance(sub, ast.Call) \
                    and dotted_name(sub.func) == "len" \
                    and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in params:
                hit = sub.args[0].id
        if hit is not None:
            out.append((hit, node))
    return out


class StaticShapeChecker(Checker):
    name = "static-shapes"
    targets = ("etcd_tpu/",)

    def check(self, relpath, tree, source, root=None, ctx=None):
        if ctx is None:
            return []
        findings: list[Finding] = []
        for scope, fn in iter_functions(tree):
            statics: tuple[str, ...] | None = None
            for dec in fn.decorator_list:
                is_root, st = _decorator_root(dec)
                if is_root:
                    statics = st
                    break
            if statics is None:
                continue
            branches = _shape_branch_params(fn, statics)
            if not branches:
                continue
            sites = ctx.callgraph.call_sites_of(relpath, scope)
            tokens = self._site_tokens(fn, sites)
            for param, test in branches:
                toks = tokens.get(param, set())
                if len(toks) >= 2:
                    findings.append(Finding(
                        checker=self.name, path=relpath,
                        line=test.lineno, rule="shape-branch",
                        scope=scope,
                        message=(
                            f"Python branch on `{param}.shape` "
                            f"inside jit root `{fn.name}` whose "
                            f"call sites pass differently-shaped "
                            f"arrays ({', '.join(sorted(toks))}) — "
                            f"every new shape re-traces and "
                            f"re-compiles; pad to one shape or "
                            f"declare the split via "
                            f"static_argnames"),
                        detail=f"{fn.name}.{param}"))
        return findings

    @staticmethod
    def _site_tokens(fn, sites) -> dict[str, set[str]]:
        """param -> set of proven shape tokens across call sites."""
        params = _param_names(fn)
        out: dict[str, set[str]] = {}
        for _rel, _scope, call in sites:
            for i, arg in enumerate(call.args):
                if i >= len(params):
                    break
                tok = shape_token(arg)
                if tok is not None:
                    out.setdefault(params[i], set()).add(tok)
            for kw in call.keywords:
                if kw.arg in params:
                    tok = shape_token(kw.value)
                    if tok is not None:
                        out.setdefault(kw.arg, set()).add(tok)
        return out
