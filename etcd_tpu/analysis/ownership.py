"""Thread-ownership checker + domain registry (PR 16 tentpole,
part 3).

PR 15's role split made several pieces of state single-writer by
*convention*: the frontdoor loop thread is the sole owner of
per-conn state, only the serving shard's apply path writes the
shm-ring head, the distpipe per-channel bookkeeping mutates only
under the owning server's thread.  Those conventions live here as
checkable facts:

- **Annotations** (in server code): ``# owner: <domain>`` trailing
  an ``self.attr = ...`` assignment declares the attribute a member
  of the domain; the same marker on a ``def`` line declares an
  owner-only method (call sites from non-owner threads are flagged).
- **Registry** (this module): ``DOMAINS`` maps each domain name to
  the thread/process roots allowed to write it — ``(relpath,
  scope)`` function keys, typically thread targets discovered by
  the call graph (``threading.Thread(target=...)``) or role
  ``main()``s listed in ``EXTRA_ROOTS``.

The checker walks forward from every root through the resolved
call-edge map (spawn boundaries cut the walk: a spawned target is a
new root, not a callee) and flags any write to a domain member from
a function reachable from a root outside the domain's owner set.
``__init__`` writes are exempt — construction happens before the
object is shared.

Suppress with ``# lint: ok(thread-ownership)`` on the write line,
or baseline with a justification.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from .concmodel import concurrency_model
from .engine import AnalysisContext, Checker, Finding

_OWNER_RE = re.compile(r"#\s*owner:\s*([A-Za-z0-9_-]+)")


@dataclass(frozen=True)
class Domain:
    """One ownership domain: the roots allowed to write it, plus an
    optional lock-guard escape.  ``guard`` names a lock id
    (``Class.attr``); when set, a NON-owner root may access the
    domain as long as that lock is held at the site (lexically or
    must-held at entry to the containing function) — the shape of
    the distpipe contract, where peerlink reader threads absorb
    acks into pipeline state but only ever under the server lock.
    Without a guard the domain is thread-exclusive (frontdoor
    per-conn state)."""

    owners: tuple[tuple[str, str], ...]  # (relpath, scope) roots
    doc: str = ""
    guard: str | None = None


#: The real tree's domains.  Owner scopes are thread-entry
#: functions (Thread targets / role mains); a domain member written
#: from any OTHER root is a finding.
DOMAINS: dict[str, Domain] = {
    "frontdoor-loop": Domain(
        owners=(
            ("etcd_tpu/server/frontdoor.py", "FrontDoor._run"),
        ),
        doc=("per-connection state (_Conn fields, conn/timer "
             "tables): written only by the frontdoor event-loop "
             "thread; workers hand results back via the _post "
             "mailbox")),
    "shmring-producer": Domain(
        owners=(
            ("etcd_tpu/server/distserver.py", "DistServer.run"),
            ("etcd_tpu/server/distserver.py",
             "_make_peer_handler.Handler.do_POST"),
        ),
        doc=("ring head/generation cursors: the serving shard's "
             "apply path publishes.  SPSC holds because every "
             "producer-side touch is serialized by the server "
             "lock (commits can also land from the ack path on "
             "peerlink reader threads — legal only under the "
             "lock, which the guard enforces)"),
        guard="DistServer.lock"),
    "shmring-consumer": Domain(
        owners=(
            ("etcd_tpu/server/roles.py", "run_worker.consume"),
        ),
        doc=("ring tail cursor: only the worker consume thread "
             "pops")),
    "ingest-lanes": Domain(
        owners=(
            ("etcd_tpu/server/roles.py", "RemoteEtcd._lane"),
        ),
        doc=("per-lane etcd_index high-water slots: each written "
             "only by its own lane thread (slot-striped, no lock); "
             "everyone else reads max()")),
    "distpipe-state": Domain(
        owners=(
            ("etcd_tpu/server/distserver.py", "DistServer.run"),
            ("etcd_tpu/server/distserver.py",
             "_make_peer_handler.Handler.do_POST"),
        ),
        doc=("append-pipeline per-peer bookkeeping: mutated from "
             "the run loop, the frame handler, AND the peerlink "
             "channel threads' ack/fail callbacks — every touch "
             "under the owning server's lock (the distpipe module "
             "docstring's contract, now checked)"),
        guard="DistServer.lock"),
}

#: Process/serve entry points the Thread(target=...) scan cannot
#: see: role mains (spawned as OS processes by the supervisor) and
#: the threaded peer-HTTP handler.
EXTRA_ROOTS: tuple[tuple[str, str], ...] = (
    ("etcd_tpu/server/roles.py", "run_shard"),
    ("etcd_tpu/server/roles.py", "run_worker"),
    ("etcd_tpu/server/roles.py", "run_ingest"),
    ("etcd_tpu/server/distserver.py",
     "_make_peer_handler.Handler.do_POST"),
)


def _iter_class_body(node: ast.ClassDef):
    """Walk a class body without descending into nested classes
    (they are their own ClassModels)."""
    stack = list(node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.ClassDef):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class OwnershipChecker(Checker):
    name = "thread-ownership"
    targets = ("etcd_tpu/",)

    def __init__(self, domains: dict[str, Domain] | None = None,
                 extra_roots: tuple | None = None):
        self.domains = DOMAINS if domains is None else domains
        self.extra_roots = EXTRA_ROOTS if extra_roots is None \
            else extra_roots
        self._cache: dict[str, dict[str, list[Finding]]] = {}

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None,
              ctx: AnalysisContext | None = None) -> list[Finding]:
        if root is None or ctx is None:
            return []
        by_file = self._cache.get(root)
        if by_file is None:
            by_file = self._analyze(root, ctx)
            self._cache[root] = by_file
        return list(by_file.get(relpath, ()))

    # ------------------------------------------------------------------

    def _collect_annotations(self, model, ctx):
        """(class, attr) -> (domain, relpath, line) for attribute
        members; (class, method) -> same for owner-only defs;
        plus a list of unknown-domain findings."""
        attrs: dict[tuple[str, str], tuple] = {}
        methods: dict[tuple[str, str], tuple] = {}
        bad: list[Finding] = []

        def domain_on(rel: str, line: int) -> str | None:
            lines = ctx.lines(rel)
            if 0 < line <= len(lines):
                m = _OWNER_RE.search(lines[line - 1])
                if m:
                    return m.group(1)
            return None

        for cm in model.classes.values():
            for n in _iter_class_body(cm.node):
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    d = domain_on(cm.relpath, n.lineno)
                    if d is None:
                        continue
                    key = (cm.name, n.name)
                    sink, scope = methods, \
                        f"{cm.scope}.{n.name}"
                elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                    tgt = n.targets[0] if isinstance(
                        n, ast.Assign) else n.target
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    d = domain_on(cm.relpath, n.lineno)
                    if d is None:
                        continue
                    key = (cm.name, tgt.attr)
                    sink, scope = attrs, cm.scope
                else:
                    continue
                if d not in self.domains:
                    bad.append(Finding(
                        checker=self.name, path=cm.relpath,
                        line=n.lineno, rule="unknown-domain",
                        scope=scope, detail=d,
                        message=(f"annotation names domain "
                                 f"{d!r} not in the ownership "
                                 f"registry (analysis/"
                                 f"ownership.py DOMAINS)")))
                    continue
                sink[key] = (d, cm.relpath, n.lineno)
        return attrs, methods, bad

    def _roots(self, model) -> set[tuple[str, str]]:
        roots: set[tuple[str, str]] = set()
        for fi in model.functions.values():
            for tkey, _name, _line in fi.spawns:
                roots.add(tkey)
        for key in getattr(model.cg, "thread_entry_points",
                           lambda: ())():
            if key in model.functions:
                roots.add(key)
        for key in self.extra_roots:
            if key in model.functions:
                roots.add(key)
        return roots

    def _analyze(self, root: str,
                 ctx: AnalysisContext) -> dict[str, list[Finding]]:
        model = concurrency_model(root, ctx)
        attrs, methods, bad = self._collect_annotations(model, ctx)
        by_file: dict[str, list[Finding]] = {}
        for f in bad:
            by_file.setdefault(f.path, []).append(f)
        if not attrs and not methods:
            return by_file

        roots = self._roots(model)
        # func key -> roots that reach it (forward BFS per root;
        # spawn boundaries were already cut in the edge map)
        reached_by: dict[tuple, set[tuple]] = {}
        for r in roots:
            seen = {r}
            frontier = [r]
            while frontier:
                k = frontier.pop()
                reached_by.setdefault(k, set()).add(r)
                for callee, _h, _l in model.functions[k].edges:
                    if callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)

        # must-held-at-entry: the lock-guard escape accepts a guard
        # the caller is merely KNOWN to hold, not only lexical holds
        entry = model.entry_held_intersection()

        def flag(fi, line, held, rule, domain, what):
            dom = self.domains[domain]
            reaching = reached_by.get((fi.relpath, fi.scope), set())
            bad_roots = sorted(
                f"{r[1]}" for r in reaching
                if r not in dom.owners)
            if not bad_roots:
                return
            if dom.guard is not None:
                held_all = frozenset(held) | entry.get(
                    (fi.relpath, fi.scope), frozenset())
                if dom.guard in held_all:
                    return
                why = (f"without its guard lock {dom.guard} "
                       f"held, from non-owner thread root(s) "
                       f"{', '.join(bad_roots[:3])}")
            else:
                why = (f"from non-owner thread root(s) "
                       f"{', '.join(bad_roots[:3])}")
            by_file.setdefault(fi.relpath, []).append(Finding(
                checker=self.name, path=fi.relpath, line=line,
                rule=rule, scope=fi.scope,
                detail=f"{domain}|{what}",
                message=(f"{what} is owned by domain "
                         f"{domain!r} but reached {why}")))

        for key, fi in model.functions.items():
            if fi.scope.split(".")[-1] == "__init__":
                continue
            for cname, attr, held, line in fi.writes:
                hit = attrs.get((cname, attr))
                if hit is None:
                    continue
                flag(fi, line, held, "non-owner-write", hit[0],
                     f"{cname}.{attr}")
            for callee, held, line in fi.edges:
                cfi = model.functions[callee]
                if not cfi.class_name:
                    continue
                m = cfi.scope.rsplit(".", 1)[-1]
                hit = methods.get((cfi.class_name, m))
                if hit is None:
                    continue
                flag(fi, line, held, "non-owner-call", hit[0],
                     f"{cfi.class_name}.{m}()")
        return by_file
