"""tracer-purity: host-sync and impurity hazards in traced code.

Roots are functions the module hands to a tracer: ``@jax.jit`` /
``@partial(jax.jit, static_argnames=...)`` decorations, and functions
passed to ``jax.jit`` / ``jax.vmap`` / ``jax.shard_map`` /
``pl.pallas_call`` call sites.  From each root a light taint walk
marks traced values: non-static parameters are tainted; assignments
propagate; ``.shape``/``.dtype``/``.ndim``/``.size`` and ``len()``
de-taint (static at trace time).  Intra-module callees invoked with a
tainted argument are visited too (their matching params tainted).

The taint walk is **whole-program** (PR 4): a callee invoked with a
tainted argument is followed even when it lives in another module —
``from X import y`` names, module-alias calls and ``__init__.py``
re-exports resolve through the project call graph
(:mod:`.callgraph`), and findings land in the file that owns the
hazard.  ``TracerPurityChecker(cross_module=False)`` restores the old
per-module walk (the fixture tests use it to prove what the
single-module view misses).

Hazards (each a finding):

- ``host-sync``: ``x.item()`` / ``np.<anything>(x)`` /
  ``np.asarray(x)`` on a tainted value — a device→host transfer that
  serializes the trace (or a silent constant-fold of a traced value).
- ``host-cast``: ``int()/float()/bool()/complex()`` of a tainted
  value — concretization error at trace time or a hidden sync.
- ``traced-branch``: Python ``if``/``while`` on a tainted test
  (``is None`` checks excluded — they are Python-level, not traced).
- ``traced-range``: ``for _ in range(tainted)`` / iterating a tainted
  value — data-dependent Python loop inside a trace.
- ``impure-call``: wall-clock/random/env reads inside traced code —
  they bake one host value into the compiled executable
  (``time.*``, ``random.*``, ``np.random.*``, ``datetime.*.now``,
  ``os.environ``/``os.getenv``, ``uuid.*``).
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions

#: attribute names whose access yields a static (host) value even on
#: a traced array
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}

#: call-wrappers that make their function argument a trace root
_ROOT_TAKERS = {"jit", "vmap", "pmap", "shard_map", "pallas_call",
                "grad", "value_and_grad", "checkpoint", "remat"}

_HOST_CASTS = {"int", "float", "bool", "complex"}

_DETAINT_CALLS = {"len", "isinstance", "type", "getattr", "hasattr"}

_IMPURE_PREFIXES = (
    "time.", "random.", "np.random", "numpy.random",
    "datetime.", "os.environ", "os.getenv", "os.urandom", "uuid.",
)


def _is_impure_call(name: str) -> bool:
    if not name:
        return False
    return any(name == p.rstrip(".") or name.startswith(p)
               for p in _IMPURE_PREFIXES)


def _decorator_root(dec: ast.AST) -> tuple[bool, tuple[str, ...]]:
    """(is-jit-root, static_argnames) for one decorator node."""
    name = dotted_name(dec)
    if name.split(".")[-1] in ("jit", "pjit"):
        return True, ()
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        # functools.partial(jax.jit, static_argnames=(...)) and
        # jax.jit(..., static_argnames=...) as a decorator factory
        inner = [dotted_name(a) for a in dec.args]
        is_partial_jit = (fname.split(".")[-1] == "partial"
                          and any(n.split(".")[-1] in ("jit", "pjit")
                                  for n in inner))
        is_jit_call = fname.split(".")[-1] in ("jit", "pjit")
        if is_partial_jit or is_jit_call:
            statics: list[str] = []
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums") \
                        and isinstance(kw.value,
                                       (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            statics.append(el.value)
                elif kw.arg == "static_argnames" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    statics.append(kw.value.value)
            return True, tuple(statics)
    return False, ()


class _FunctionIndex:
    """name → [(scope, node)] for every def in the module."""

    def __init__(self, tree: ast.AST):
        self.by_name: dict[str, list] = {}
        for scope, node in iter_functions(tree):
            self.by_name.setdefault(node.name, []).append(
                (scope, node))


class _TaintVisitor(ast.NodeVisitor):
    def __init__(self, checker: "TracerPurityChecker", relpath: str,
                 scope: str, node: ast.AST, tainted: set[str],
                 index: _FunctionIndex, findings: list[Finding],
                 visited: set, ctx=None):
        self.c = checker
        self.relpath = relpath
        self.scope = scope
        self.tainted = set(tainted)
        self.index = index
        self.findings = findings
        self.visited = visited
        self.ctx = ctx
        self._body(node)

    # -- taint rules -----------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname.split(".")[-1] in _DETAINT_CALLS:
                return False
            if self.is_tainted(node.func):
                return True
            return any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(k.value) for k in node.keywords)
        if isinstance(node, ast.Subscript):
            return (self.is_tainted(node.value)
                    or self.is_tainted(node.slice))
        if isinstance(node, (ast.BinOp,)):
            return (self.is_tainted(node.left)
                    or self.is_tainted(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity checks are Python-level, never traced
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return (self.is_tainted(node.left)
                    or any(self.is_tainted(c)
                           for c in node.comparators))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self.is_tainted(node.body)
                    or self.is_tainted(node.orelse))
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.is_tainted(node.value)
        return False

    def _flag(self, node: ast.AST, rule: str, message: str,
              detail: str) -> None:
        self.findings.append(Finding(
            checker=self.c.name, path=self.relpath,
            line=getattr(node, "lineno", 0), rule=rule,
            scope=self.scope, message=message, detail=detail))

    def _body(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            self.visit(node.body)
            return
        for stmt in node.body:
            self.visit(stmt)

    # -- statements ------------------------------------------------------

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        t = self.is_tainted(node.value)
        for target in node.targets:
            self._bind(target, t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if self.is_tainted(node.value):
            self._bind(node.target, True)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._bind(node.target, self.is_tainted(node.value))

    def visit_If(self, node: ast.If) -> None:
        if self.is_tainted(node.test):
            self._flag(node, "traced-branch",
                       "Python `if` on a traced value inside jitted "
                       "code — use jnp.where/lax.cond",
                       ast.unparse(node.test)[:60])
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self.is_tainted(node.test):
            self._flag(node, "traced-branch",
                       "Python `while` on a traced value inside "
                       "jitted code — use lax.while_loop",
                       ast.unparse(node.test)[:60])
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        # NOTE: iterating a tainted value is NOT flagged — tuples of
        # traced pytrees (`for st in states`) are idiomatic jax; only
        # a data-dependent `range()` bound is a real hazard
        it = node.iter
        if isinstance(it, ast.Call) \
                and dotted_name(it.func) == "range":
            if any(self.is_tainted(a) for a in it.args):
                self._flag(node, "traced-range",
                           "`range()` over a traced value — "
                           "data-dependent Python loop in a trace",
                           ast.unparse(it)[:60])
        self.generic_visit(node)

    # -- calls -----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fname = dotted_name(node.func)
        leaf = fname.split(".")[-1]

        if _is_impure_call(fname):
            self._flag(node, "impure-call",
                       f"impure call `{fname}` inside traced code — "
                       f"the traced value is frozen at compile time",
                       fname)

        # x.item(): device→host sync
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" \
                and self.is_tainted(node.func.value):
            self._flag(node, "host-sync",
                       "`.item()` on a traced value — host sync "
                       "inside jitted code",
                       ast.unparse(node.func.value)[:60])

        # int()/float()/bool() on a traced value
        if isinstance(node.func, ast.Name) \
                and node.func.id in _HOST_CASTS and node.args \
                and self.is_tainted(node.args[0]):
            self._flag(node, "host-cast",
                       f"`{node.func.id}()` of a traced value — "
                       f"concretization/sync inside jitted code",
                       ast.unparse(node.args[0])[:60])

        # np.* applied to a traced value (np.asarray included)
        root = fname.split(".")[0]
        if root in ("np", "numpy", "onp") and (
                any(self.is_tainted(a) for a in node.args)
                or any(self.is_tainted(k.value)
                       for k in node.keywords)):
            self._flag(node, "host-sync",
                       f"`{fname}()` on a traced value — host numpy "
                       f"op inside jitted code",
                       fname)

        # follow callees invoked with tainted args: intra-module
        # first, then across module boundaries via the call graph
        tainted_args = [self.is_tainted(a) for a in node.args]
        tainted_kws = any(self.is_tainted(k.value)
                          for k in node.keywords)
        if any(tainted_args) or tainted_kws:
            if isinstance(node.func, ast.Name) \
                    and node.func.id in self.index.by_name:
                for scope, fn in self.index.by_name[node.func.id]:
                    self.c._visit_function(
                        self.relpath, scope, fn,
                        self._callee_taint(fn, node, tainted_args),
                        self.index, self.findings, self.visited,
                        leaf, ctx=self.ctx)
            elif self.c.cross_module and self.ctx is not None:
                cg = self.ctx.callgraph
                for rel2, scope2, fn2 in cg.resolve_call(
                        self.relpath, fname):
                    if isinstance(fn2, ast.Lambda):
                        continue
                    mi2 = cg.module(rel2)
                    if mi2 is None:
                        continue
                    # ModuleInfo exposes the same by_name map a
                    # _FunctionIndex would — no second index cache
                    self.c._visit_function(
                        rel2, scope2, fn2,
                        self._callee_taint(fn2, node, tainted_args),
                        mi2, self.findings, self.visited, leaf,
                        ctx=self.ctx)
        self.generic_visit(node)

    def _callee_taint(self, fn, call: ast.Call,
                      tainted_args: list[bool]) -> set[str]:
        params = [a.arg for a in fn.args.args]
        out = set()
        for i, t in enumerate(tainted_args):
            if t and i < len(params):
                out.add(params[i])
        for kw in call.keywords:
            if kw.arg and kw.arg in params \
                    and self.is_tainted(kw.value):
                out.add(kw.arg)
        return out

    # nested defs: visited when called/passed, not on definition
    def visit_FunctionDef(self, node):  # noqa: D102
        pass

    def visit_AsyncFunctionDef(self, node):  # noqa: D102
        pass

    def visit_Lambda(self, node):  # noqa: D102
        pass


class TracerPurityChecker(Checker):
    name = "tracer-purity"
    targets = (
        "etcd_tpu/ops/",
        "etcd_tpu/raft/batched.py",
        "etcd_tpu/raft/multiraft.py",
        "etcd_tpu/wal/replay_device.py",
        "etcd_tpu/parallel/mesh.py",
    )

    def __init__(self, cross_module: bool = True):
        #: follow tainted calls across module boundaries via the
        #: project call graph; False = the pre-PR-4 per-module walk
        self.cross_module = cross_module

    def check(self, relpath, tree, source, root=None, ctx=None):
        findings: list[Finding] = []
        index = _FunctionIndex(tree)
        visited: set[tuple[str, frozenset]] = set()
        roots = self._find_roots(tree, index)
        for scope, node, statics in roots:
            tainted = self._param_taint(node, statics)
            self._visit_function(relpath, scope, node, tainted,
                                 index, findings, visited, "root",
                                 ctx=ctx)
        # de-dup identical findings found via multiple paths
        seen = set()
        out = []
        for f in findings:
            key = (f.fingerprint, f.line)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    @staticmethod
    def _param_taint(node, statics) -> set[str]:
        if isinstance(node, ast.Lambda):
            return {a.arg for a in node.args.args}
        args = node.args
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        return {n for n in names if n not in statics
                and n not in ("self", "cls")}

    def _find_roots(self, tree, index):
        roots = []
        for scope, node in iter_functions(tree):
            for dec in node.decorator_list:
                is_root, statics = _decorator_root(dec)
                if is_root:
                    roots.append((scope, node, statics))
                    break
        # functions passed to jit/vmap/shard_map/pallas_call(...)
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            leaf = dotted_name(call.func).split(".")[-1]
            if leaf not in _ROOT_TAKERS:
                continue
            for arg in call.args:
                if isinstance(arg, ast.Name) \
                        and arg.id in index.by_name:
                    for scope, fn in index.by_name[arg.id]:
                        roots.append((scope, fn, ()))
                elif isinstance(arg, ast.Lambda):
                    roots.append(("<lambda>", arg, ()))
            for kw in call.keywords:
                if isinstance(kw.value, ast.Name) \
                        and kw.value.id in index.by_name:
                    for scope, fn in index.by_name[kw.value.id]:
                        roots.append((scope, fn, ()))
        # stable de-dup by (scope, id)
        seen = set()
        out = []
        for scope, node, statics in roots:
            if id(node) not in seen:
                seen.add(id(node))
                out.append((scope, node, statics))
        return out

    def _visit_function(self, relpath, scope, node, tainted, index,
                        findings, visited, via, ctx=None) -> None:
        key = (id(node), frozenset(tainted))
        if key in visited or len(visited) > 4000:
            return
        visited.add(key)
        _TaintVisitor(self, relpath, scope, node, tainted, index,
                      findings, visited, ctx=ctx)
