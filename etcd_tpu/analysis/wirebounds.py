"""wire-bounds: wire-derived counts must be bounds-checked before
they size anything.

A parse scope (analysis/wiremodel.py) turns bytes an attacker or a
crashed peer controls into integers.  Any such integer that reaches a
``range()``, a ``frombuffer(count=...)``, a ``bytearray``/``bytes``
allocation, a ``np.zeros``-style allocation, or a sequence-repeat
(``b"\\0" * n``) without a dominating guard is a finding: a 24-byte
hostile frame must never drive a multi-GiB allocation or a 2^31-turn
loop.  Guards are (a) a raising ``if`` that compares the value
(typically against ``len(data)``) or (b) a schema plausibility cap
via ``wire/schema.py``'s ``check_bound``.

The schema's ``BOUNDS`` catalog is a closed vocabulary, checked both
ways (the fault-vocabulary pattern, PR 10): every ``check_bound``
call site must name a declared bound with a string literal
(``dynamic-bound-name`` / ``unregistered-bound``), and every bound
the schema declares for this module must actually be enforced in its
declared scope (``missing-plausibility-cap``) — so adding a schema
cap without wiring the rejection, or vice versa, fails lint.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map
from .wiremodel import (SCHEMA_RELPATH, WIRE_TARGETS, module_schema,
                        parse_scopes)
from ..wire import schema as _schema

#: calls whose results are wire-derived integers
_SOURCE_LAST = {"unpack_from", "unpack", "uvarint", "parse_header",
                "_parse_header", "_tag"}
_ALLOC_LAST = {"zeros", "empty", "full"}


def _names(expr: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            d = dotted_name(n)
            if d:
                out.add(d)
    return out


def _has_source_call(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        last = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if last in _SOURCE_LAST or last.startswith("_view_"):
            return True
    return False


def _has_len_call(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Name)
               and n.func.id == "len"
               for n in ast.walk(expr))


def _raises(body: list[ast.stmt]) -> bool:
    return any(isinstance(n, (ast.Raise, ast.Return))
               for stmt in body for n in ast.walk(stmt))


def _target_names(t: ast.AST) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, ast.Attribute):
        d = dotted_name(t)
        return [d] if d else []
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


class _TaintWalk:
    """Per-function lexical taint walk: statement order, loops and
    branches included; guard state is per tainted name."""

    def __init__(self, checker: "WireBoundsChecker", relpath: str,
                 scope: str, out: list[Finding]):
        self.checker = checker
        self.relpath = relpath
        self.scope = scope
        self.out = out
        #: tainted name -> guarded?
        self.taint: dict[str, bool] = {}

    def _tainted(self, expr: ast.AST) -> set[str]:
        return _names(expr) & set(self.taint)

    def _unguarded_in(self, expr: ast.AST) -> str | None:
        for name in sorted(self._tainted(expr)):
            if not self.taint[name]:
                return name
        return None

    def _finding(self, node: ast.AST, sink: str, name: str) -> None:
        self.out.append(Finding(
            checker=self.checker.name, path=self.relpath,
            line=node.lineno, rule="unchecked-wire-count",
            scope=self.scope,
            message=f"wire-derived {name!r} reaches {sink} without "
                    f"a dominating length check or schema "
                    f"plausibility cap (wire/schema.py check_bound)",
            detail=f"{sink}:{name}"))

    def _scan_sinks(self, expr: ast.AST) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                f = n.func
                last = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if last == "range":
                    for a in n.args:
                        bad = self._unguarded_in(a)
                        if bad:
                            self._finding(n, "range", bad)
                            break
                elif last == "frombuffer":
                    for kw in n.keywords:
                        if kw.arg == "count":
                            bad = self._unguarded_in(kw.value)
                            if bad:
                                self._finding(n, "frombuffer-count",
                                              bad)
                elif last in ("bytearray", "bytes"):
                    if n.args and not isinstance(n.args[0],
                                                 ast.Subscript):
                        bad = self._unguarded_in(n.args[0])
                        if bad:
                            self._finding(n, "allocation", bad)
                elif last in _ALLOC_LAST:
                    if n.args:
                        bad = self._unguarded_in(n.args[0])
                        if bad:
                            self._finding(n, "allocation", bad)
            elif isinstance(n, ast.BinOp) \
                    and isinstance(n.op, ast.Mult):
                for side, other in ((n.left, n.right),
                                    (n.right, n.left)):
                    if isinstance(side, (ast.List, ast.Constant)) \
                            and isinstance(
                                getattr(side, "value", []),
                                (bytes, str, list)):
                        bad = self._unguarded_in(other)
                        if bad:
                            self._finding(n, "sequence-repeat", bad)

    def _mark_check_bound(self, expr: ast.AST) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                f = n.func
                last = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if last == "check_bound" and len(n.args) >= 2:
                    for name in self._tainted(n.args[1]):
                        self.taint[name] = True

    def _assign(self, targets: list[ast.AST],
                value: ast.AST | None) -> None:
        if value is None:
            return
        names = [t for tgt in targets for t in _target_names(tgt)]
        if _has_source_call(value):
            for t in names:
                self.taint[t] = False
            return
        refs = self._tainted(value)
        if refs:
            guarded = all(self.taint[r] for r in refs)
            for t in names:
                self.taint[t] = guarded
        else:
            for t in names:
                self.taint.pop(t, None)

    def block(self, stmts: list[ast.stmt]) -> None:
        for s in stmts:
            if isinstance(s, ast.Assign):
                self._scan_sinks(s.value)
                self._mark_check_bound(s.value)
                self._assign(s.targets, s.value)
            elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                if s.value is not None:
                    self._scan_sinks(s.value)
                self._assign([s.target], s.value)
            elif isinstance(s, ast.Expr):
                self._mark_check_bound(s.value)
                self._scan_sinks(s.value)
            elif isinstance(s, (ast.If, ast.While)):
                self._scan_sinks(s.test)
                if _raises(s.body) or _raises(s.orelse):
                    # a raising comparison dominates everything
                    # after it: the value was rejected or bounded
                    for name in self._tainted(s.test):
                        self.taint[name] = True
                self.block(s.body)
                self.block(s.orelse)
            elif isinstance(s, ast.For):
                self._scan_sinks(s.iter)
                refs = self._tainted(s.iter)
                if refs:
                    guarded = all(self.taint[r] for r in refs)
                    for t in _target_names(s.target):
                        self.taint[t] = guarded
                self.block(s.body)
                self.block(s.orelse)
            elif isinstance(s, ast.Try):
                self.block(s.body)
                for h in s.handlers:
                    self.block(h.body)
                self.block(s.orelse)
                self.block(s.finalbody)
            elif isinstance(s, ast.With):
                for item in s.items:
                    self._scan_sinks(item.context_expr)
                self.block(s.body)
            elif isinstance(s, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                self.block(s.body)
            elif isinstance(s, (ast.Return, ast.Raise)):
                pass  # escaping values are the caller's wire data
            elif isinstance(s, ast.Assert):
                self._scan_sinks(s.test)


class WireBoundsChecker(Checker):
    name = "wire-bounds"
    targets = WIRE_TARGETS

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx=None) -> list[Finding]:
        if relpath == SCHEMA_RELPATH:
            return []
        out: list[Finding] = []
        scopes = parse_scopes(relpath, tree, ctx)
        for scope, fn in scopes.items():
            walk = _TaintWalk(self, relpath, scope, out)
            walk.block(fn.body)
        self._check_vocab(relpath, tree, out)
        self._check_coverage(relpath, tree, scopes, out)
        return out

    # -- closed bound vocabulary (the fault-catalog pattern) ------------

    def _check_vocab(self, relpath: str, tree: ast.AST,
                     out: list[Finding]) -> None:
        owner = scope_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            last = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if last != "check_bound" or not node.args:
                continue
            scope = owner.get(node, "")
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="dynamic-bound-name",
                    scope=scope,
                    message="check_bound(<non-literal>) — bound "
                            "names must be string literals from "
                            "wire/schema.py's BOUNDS",
                    detail="check_bound"))
            elif arg.value not in _schema.BOUNDS:
                out.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="unregistered-bound",
                    scope=scope,
                    message=f"bound {arg.value!r} is not declared "
                            f"in wire/schema.py's BOUNDS — "
                            f"check_bound would KeyError at parse "
                            f"time",
                    detail=arg.value))

    # -- every declared bound is enforced where the schema says ---------

    def _bound_used(self, node: ast.AST, key: str) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                f = n.func
                last = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if last == "check_bound" and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and n.args[0].value == key:
                    return True
            elif isinstance(n, ast.Subscript):
                base = dotted_name(n.value)
                if base.rsplit(".", 1)[-1] == "BOUNDS" \
                        and isinstance(n.slice, ast.Constant) \
                        and n.slice.value == key:
                    return True
        return False

    def _check_coverage(self, relpath: str, tree: ast.AST,
                        scopes: dict[str, ast.AST],
                        out: list[Finding]) -> None:
        sch = module_schema(relpath)
        if sch is None or not scopes:
            return
        for bound in sch.bounds:
            if bound.scope:
                fn = scopes.get(bound.scope)
                if fn is None:
                    continue  # scope absent (partial fixture tree)
                node, line = fn, fn.lineno
            else:
                node, line = tree, 1
            if not self._bound_used(node, bound.name):
                out.append(Finding(
                    checker=self.name, path=relpath, line=line,
                    rule="missing-plausibility-cap",
                    scope=bound.scope,
                    message=f"schema bound {bound.name!r} "
                            f"({bound.doc or 'wire count'}, cap "
                            f"{bound.cap}) is never enforced in "
                            f"{bound.scope or relpath} — add "
                            f"check_bound({bound.name!r}, ...)",
                    detail=bound.name))
