"""Shared plumbing for the wire checkers (PR 19): which files are
wire targets, which functions are parse scopes, and the per-module
schema/typed-error lookup.

A *parse scope* is a function that turns attacker-controllable bytes
into values: the schema (wire/schema.py) pins the real modules' entry
points exactly via ``parse_scopes``, the ``PARSE_NAME_RE`` name
convention covers fixture trees and newly added helpers, and the call
graph (PR 4) closes over helpers a declared entry calls inside the
wire targets — so a parse path can't dodge the checkers by moving its
body into an oddly named local function.
"""

from __future__ import annotations

import ast

from .engine import iter_functions
from ..wire import schema

#: the five formats' homes — every wire checker targets exactly these
WIRE_TARGETS = ("etcd_tpu/wire/", "etcd_tpu/server/shmring.py")

#: the schema module itself is the one wire file that legitimately
#: declares layout literals
SCHEMA_RELPATH = "etcd_tpu/wire/schema.py"


def module_schema(relpath: str) -> schema.FrameSchema | None:
    return schema.MODULE_SCHEMAS.get(relpath)


def typed_error(relpath: str) -> str:
    sch = module_schema(relpath)
    return sch.error if sch else "FrameError"


def parse_scopes(relpath: str, tree: ast.AST,
                 ctx=None) -> dict[str, ast.AST]:
    """{scope: function node} for every parse scope in the file."""
    sch = module_schema(relpath)
    declared = set(sch.parse_scopes) if sch else set()
    funcs = dict(iter_functions(tree))
    out: dict[str, ast.AST] = {}
    for scope, fn in funcs.items():
        base = scope.rsplit(".", 1)[-1]
        if scope in declared or schema.PARSE_NAME_RE.match(base):
            out[scope] = fn
    if ctx is None or not declared:
        return out
    # call-graph closure: helpers a declared entry scope calls, when
    # they live in a wire target file (same-file helpers surface as
    # scopes here; cross-file ones are checked in their own file's
    # pass since the lint run visits every wire target)
    frontier = list(out.items())
    while frontier:
        _scope, fn = frontier.pop()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            try:
                defs = ctx.callgraph.resolve_call(relpath,
                                                  node.func.id)
            except Exception:  # pragma: no cover - defensive
                continue
            for dpath, dscope, _dnode in defs:
                if dpath == relpath and dscope in funcs \
                        and dscope not in out:
                    out[dscope] = funcs[dscope]
                    frontier.append((dscope, funcs[dscope]))
    return out
