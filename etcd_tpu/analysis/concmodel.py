"""Whole-program concurrency model shared by the lock-order,
blocking-under-lock and thread-ownership checkers (PR 16).

The class-local lock-discipline checker (locks.py) sees one class at
a time; the PR-15 role split spread the locking story across modules
(store world lock <- server lock <- peerlink channel state), so the
three concurrency checkers need one *global* view:

- every lock object in the project (``self.x = threading.Lock()``
  class attributes AND module-level ``_lock = threading.Lock()``),
- per function: the lexically-held lock set at every acquisition,
  call, blocking operation and attribute write,
- a function-level call-edge map that crosses modules (resolved
  through the import/call graph), classes (typed ``self.attr`` and
  annotated parameters/locals) and closures (nested defs inherit
  their definition site as a call edge),
- thread-spawn sites (``threading.Thread(target=...)``) — spawn
  targets are roots of NEW threads, so call edges into them are
  dropped: a spawner's held locks are not held in the child.

Built once per :class:`~.engine.AnalysisContext` (cached on the
context, lock-guarded — the parallel checker fan-out in
``run_checkers`` may ask from several threads at once).
"""

from __future__ import annotations

import ast
import re
import threading

from .engine import dotted_name
from .purity import _decorator_root

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

#: blocking calls by dotted-name (module function form)
_BLOCKING_DOTTED = {
    "time.sleep": ("sleep", "time.sleep"),
    "sleep": ("sleep", "time.sleep"),
    "os.fsync": ("fsio", "os.fsync"),
    "os.fdatasync": ("fsio", "os.fdatasync"),
    "fsync": ("fsio", "os.fsync"),
    "socket.create_connection": ("socket",
                                 "socket.create_connection"),
    "create_connection": ("socket", "socket.create_connection"),
}

#: blocking calls by method name (``<recv>.sendall(...)`` form)
_BLOCKING_METHODS = {
    "sendall": "socket",
    "recv": "socket",
    "recvfrom": "socket",
    "accept": "socket",
    "connect": "socket",
    "fsync": "fsio",
}

_TYPE_TOKEN = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _annotation_class(node: ast.AST | None) -> str | None:
    """Bare class name out of a parameter/attribute annotation:
    ``Foo``, ``"Foo"``, ``mod.Foo``, ``Foo | None``,
    ``"Foo | None"``, ``Optional[Foo]``."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        m = _TYPE_TOKEN.search(node.value)
        return m.group(0) if m else None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.BinOp):  # X | None
        return _annotation_class(node.left)
    if isinstance(node, ast.Subscript):  # Optional[X] / list[X]
        base = dotted_name(node.value).split(".")[-1]
        if base == "Optional":
            return _annotation_class(node.slice)
        return None
    return None


class ClassModel:
    """One project class: its lock/queue/typed attributes."""

    __slots__ = ("name", "relpath", "scope", "locks", "attr_types",
                 "queues", "methods", "node", "init_params",
                 "param_attrs")

    def __init__(self, name: str, relpath: str, scope: str,
                 node: ast.ClassDef):
        self.name = name
        self.relpath = relpath
        self.scope = scope          # class path inside the module
        self.node = node
        self.locks: set[str] = set()
        self.attr_types: dict[str, str] = {}
        #: queue attrs: attr -> bounded? (maxsize given and nonzero)
        self.queues: dict[str, bool] = {}
        self.methods: set[str] = set()
        #: __init__ positional parameter order (self excluded)
        self.init_params: list[str] = []
        #: __init__ param name -> the self.attr it is stored to
        #: (``self._on_resp = on_resp or default`` included) — the
        #: callback-binding half of ctor-callback edge resolution
        self.param_attrs: dict[str, str] = {}


class FuncInfo:
    """One function's concurrency-relevant events, with the
    lexically-held lock set at each."""

    __slots__ = ("relpath", "scope", "node", "class_name",
                 "acquires", "raw_calls", "edges", "blocking",
                 "writes", "spawns", "is_spawn_target", "var_types",
                 "var_elem_types", "local_queues",
                 "ctor_callbacks")

    def __init__(self, relpath: str, scope: str, node):
        self.relpath = relpath
        self.scope = scope
        self.node = node
        self.class_name = ""       # bare enclosing class name or ""
        #: [(lock_id, held_tuple, line)]
        self.acquires: list[tuple] = []
        #: [(kind, data, held_tuple, line)]  (resolved into edges)
        self.raw_calls: list[tuple] = []
        #: [((relpath, scope), held_tuple, line)]
        self.edges: list[tuple] = []
        #: [(category, op, held_tuple, line)]
        self.blocking: list[tuple] = []
        #: [(class_name, attr, held_tuple, line)]
        self.writes: list[tuple] = []
        #: [((relpath, scope), thread_name, line)]
        self.spawns: list[tuple] = []
        self.is_spawn_target = False
        self.var_types: dict[str, str] = {}
        #: list-valued locals -> their element class
        self.var_elem_types: dict[str, str] = {}
        self.local_queues: dict[str, bool] = {}
        #: ctor sites passing callables: (class_name, param_name,
        #: target_spec, line) where target_spec is ("self", m) |
        #: ("name", n)
        self.ctor_callbacks: list[tuple] = []


def _is_thread_ctor(name: str) -> bool:
    last = name.split(".")[-1]
    return last in ("Thread", "Process") and (
        "." not in name or name.split(".")[0] in
        ("threading", "multiprocessing", "mp"))


def _queue_ctor_bound(node: ast.Call) -> bool | None:
    """None if not a queue ctor; else True when bounded."""
    last = dotted_name(node.func).split(".")[-1]
    if last not in ("Queue", "LifoQueue", "PriorityQueue",
                    "SimpleQueue"):
        return None
    bounded = False
    for a in node.args[:1]:
        if not (isinstance(a, ast.Constant) and a.value in (0, None)):
            bounded = True
    for kw in node.keywords:
        if kw.arg == "maxsize" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value in (0, None)):
            bounded = True
    return bounded


class _FuncScan(ast.NodeVisitor):
    """One pass over a single function body (nested defs excluded —
    they are scanned as their own functions, linked by a def-site
    call edge)."""

    def __init__(self, model: "ConcurrencyModel", fi: FuncInfo,
                 cls: ClassModel | None):
        self.model = model
        self.fi = fi
        self.cls = cls
        self.held: tuple[str, ...] = ()

    # -- typing helpers ---------------------------------------------------

    def _var_class(self, name: str) -> ClassModel | None:
        t = self.fi.var_types.get(name)
        return self.model.classes.get(t) if t else None

    def _lock_id_of(self, node: ast.AST) -> str | None:
        """lock id for a ``with``/``.acquire()`` receiver
        expression, or None when it isn't a known lock."""
        attr = _self_attr(node)
        if attr is not None:
            if self.cls is not None and attr in self.cls.locks:
                return f"{self.cls.name}.{attr}"
            return None
        if isinstance(node, ast.Name):
            key = (self.fi.relpath, node.id)
            if key in self.model.module_locks:
                return f"{self.fi.relpath}:{node.id}"
            c = self._var_class(node.id)
            return None if c is None else None
        if isinstance(node, ast.Attribute):
            base = node.value
            # self.a.b — typed attribute's lock
            a = _self_attr(base)
            if a is not None and self.cls is not None:
                t = self.model.classes.get(
                    self.cls.attr_types.get(a, ""))
                if t is not None and node.attr in t.locks:
                    return f"{t.name}.{node.attr}"
                return None
            if isinstance(base, ast.Name):
                c = self._var_class(base.id)
                if c is not None and node.attr in c.locks:
                    return f"{c.name}.{node.attr}"
        return None

    # -- lexical lock tracking --------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lid = self._lock_id_of(item.context_expr)
            if lid is not None:
                self.fi.acquires.append(
                    (lid, self.held, node.lineno))
                acquired.append(lid)
            else:
                self.visit(item.context_expr)
        prev = self.held
        self.held = prev + tuple(a for a in acquired
                                 if a not in prev)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    visit_AsyncWith = visit_With

    # -- writes ------------------------------------------------------------

    def _record_write(self, target: ast.AST, line: int) -> None:
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            attr = _self_attr(node)
            if attr is not None:
                if self.cls is not None \
                        and attr not in self.cls.locks:
                    self.fi.writes.append(
                        (self.cls.name, attr, self.held, line))
                return
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name):
                c = self._var_class(node.value.id)
                if c is not None and node.attr not in c.locks:
                    self.fi.writes.append(
                        (c.name, node.attr, self.held, line))
                return
            if isinstance(node, ast.Attribute) \
                    and self.cls is not None:
                # self.member.attr = ...: the write lands on the
                # MEMBER's class (when its type is known), not ours
                a2 = _self_attr(node.value)
                if a2 is not None:
                    c = self.model.classes.get(
                        self.cls.attr_types.get(a2, ""))
                    if c is not None:
                        if node.attr not in c.locks:
                            self.fi.writes.append(
                                (c.name, node.attr, self.held,
                                 line))
                        return
            node = node.value

    def _call_result_class(self, call: ast.Call) -> str | None:
        """Class name a call expression produces: direct ctor,
        classmethod ctor, or an annotated project return type."""
        parts = dotted_name(call.func).split(".")
        if parts[-1] in self.model.classes:
            return parts[-1]
        if parts[0] in self.model.classes:  # WAL.create(...)
            return parts[0]
        for _r, _s, d in self.model.resolve_name(
                self.fi.relpath, dotted_name(call.func)):
            t = _annotation_class(getattr(d, "returns", None))
            if t in self.model.classes:
                return t
        return None

    def _infer_local(self, name: str, value: ast.AST) -> None:
        if isinstance(value, ast.Call):
            qb = _queue_ctor_bound(value)
            if qb is not None:
                self.fi.local_queues[name] = qb
                return
            t = self._call_result_class(value)
            if t is not None:
                self.fi.var_types[name] = t
                return
        if isinstance(value, (ast.ListComp, ast.List)):
            elts = ([value.elt] if isinstance(value, ast.ListComp)
                    else value.elts[:1])
            for el in elts:
                if isinstance(el, ast.Call):
                    t = self._call_result_class(el)
                    if t is not None:
                        self.fi.var_elem_types[name] = t
            return
        attr = _self_attr(value)
        if attr is not None and self.cls is not None:
            t = self.cls.attr_types.get(attr)
            if t in self.model.classes:
                self.fi.var_types[name] = t
            if attr in self.cls.queues:
                self.fi.local_queues[name] = self.cls.queues[attr]

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    self._record_write(el, node.lineno)
            else:
                self._record_write(t, node.lineno)
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self._infer_local(node.targets[0].id, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record_write(node.target, node.lineno)
        if isinstance(node.target, ast.Name):
            t = _annotation_class(node.annotation)
            if t in self.model.classes:
                self.fi.var_types[node.target.id] = t
            if node.value is not None:
                self._infer_local(node.target.id, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        # ``for x in ...`` clobbers any prior local typing of x
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                self.fi.var_types.pop(n.id, None)
                self.fi.local_queues.pop(n.id, None)
        # ... unless the iterable's element class is known:
        # ``for ring in rings`` / ``for i, ring in enumerate(rings)``
        it, tgt = node.iter, node.target
        if isinstance(it, ast.Call) \
                and dotted_name(it.func) == "enumerate" \
                and it.args:
            it = it.args[0]
            if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2:
                tgt = tgt.elts[1]
        if isinstance(it, ast.Name) and isinstance(tgt, ast.Name):
            t = self.fi.var_elem_types.get(it.id)
            if t is not None:
                self.fi.var_types[tgt.id] = t
        self.generic_visit(node)

    # -- calls, blocking ops, spawns ---------------------------------------

    def _queue_recv_bounded(self, recv: ast.AST) -> bool | None:
        """None when the receiver is not a known queue; else its
        boundedness."""
        attr = _self_attr(recv)
        if attr is not None and self.cls is not None:
            return self.cls.queues.get(attr)
        if isinstance(recv, ast.Name):
            return self.fi.local_queues.get(recv.id)
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name):
            c = self._var_class(recv.value.id)
            if c is not None:
                return c.queues.get(recv.attr)
            a = _self_attr(recv)
        a = _self_attr(recv)
        if a is not None and self.cls is not None:
            t = self.model.classes.get(self.cls.attr_types.get(a, ""))
            if t is not None:
                return t.queues.get(recv.attr)
        return None

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        line = node.lineno
        name = dotted_name(f)

        # thread spawn: threading.Thread(target=...)
        if name and _is_thread_ctor(name):
            self._record_spawn(node)
            self.generic_visit(node)
            return

        # project-class construction passing callables: record the
        # (class, param) -> callback target bindings so calls
        # through the stored attr (``self._on_resp(...)`` inside
        # PipeChannel, wired at a DistServer ctor site) resolve to
        # real edges — these fire on the CONSTRUCTED object's
        # threads, which is exactly where ownership and lock-order
        # need them
        cls_name = ""
        if name:
            parts = name.split(".")
            if parts[-1] in self.model.classes:
                cls_name = parts[-1]
        if cls_name:
            target_cm = self.model.classes[cls_name]
            for i, a in enumerate(node.args):
                spec = self._callable_spec(a)
                if spec and i < len(target_cm.init_params):
                    self.fi.ctor_callbacks.append(
                        (cls_name, target_cm.init_params[i],
                         spec, line))
            for kw in node.keywords:
                spec = self._callable_spec(kw.value)
                if spec and kw.arg:
                    self.fi.ctor_callbacks.append(
                        (cls_name, kw.arg, spec, line))

        # module-function blocking ops first (``time.sleep(...)``,
        # ``os.fsync(fd)``, ``subprocess.run(...)`` — Attribute or
        # bare-Name func nodes alike)
        dotted_blocked = False
        if name:
            if name.split(".")[0] == "subprocess":
                self.fi.blocking.append(
                    ("subprocess", name, self.held, line))
                dotted_blocked = True
            elif name in _BLOCKING_DOTTED:
                cat, op = _BLOCKING_DOTTED[name]
                self.fi.blocking.append((cat, op, self.held, line))
                dotted_blocked = True

        if isinstance(f, ast.Attribute):
            m = f.attr
            # lock.acquire(): an acquisition event (held set edge
            # source), conservatively not extending the held span
            if m == "acquire":
                lid = self._lock_id_of(f.value)
                if lid is not None:
                    self.fi.acquires.append((lid, self.held, line))
            # blocking queue get/put
            if m in ("get", "put"):
                qb = self._queue_recv_bounded(f.value)
                if qb is not None:
                    nonblock = any(
                        kw.arg == "block" and isinstance(
                            kw.value, ast.Constant)
                        and kw.value.value is False
                        for kw in node.keywords) or (
                        node.args and isinstance(
                            node.args[0], ast.Constant)
                        and node.args[0].value is False
                        and m == "get")
                    if not nonblock and (m == "get" or qb):
                        self.fi.blocking.append(
                            ("queue", f"queue.{m}", self.held,
                             line))
            elif m in _BLOCKING_METHODS and not dotted_blocked:
                self.fi.blocking.append(
                    (_BLOCKING_METHODS[m], f".{m}", self.held,
                     line))

            # call edges by receiver
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.fi.raw_calls.append(
                    (("self", m), self.held, line))
            else:
                a = _self_attr(recv)
                if a is not None:
                    self.fi.raw_calls.append(
                        (("attr", a, m), self.held, line))
                elif isinstance(recv, ast.Name):
                    # typed local first; falls back to a dotted
                    # (module-receiver) lookup at resolve time
                    self.fi.raw_calls.append(
                        (("var", recv.id, m, name), self.held,
                         line))
                elif name:
                    self.fi.raw_calls.append(
                        (("dotted", name), self.held, line))
        elif name:
            self.fi.raw_calls.append((("dotted", name), self.held,
                                      line))
        self.generic_visit(node)

    def _callable_spec(self, value: ast.AST):
        """("self", m) / ("name", f) when the argument is a bound
        method, a bare function, or a lambda wrapping one."""
        attr = _self_attr(value)
        if attr is not None:
            return ("self", attr)
        if isinstance(value, ast.Lambda):
            for sub in ast.walk(value.body):
                if isinstance(sub, ast.Call):
                    a = _self_attr(sub.func)
                    if a is not None:
                        return ("self", a)
                    n = dotted_name(sub.func)
                    if n and "." not in n:
                        return ("name", n)
            return None
        if isinstance(value, ast.Name):
            return ("name", value.id)
        return None

    def _record_spawn(self, node: ast.Call) -> None:
        target = None
        tname = ""
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "name":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    tname = kw.value.value
                elif isinstance(kw.value, ast.JoinedStr):
                    tname = "".join(
                        v.value if isinstance(v, ast.Constant)
                        else "*" for v in kw.value.values)
        if target is None:
            return
        key = self.model._resolve_target(self.fi, self.cls, target)
        if key is not None:
            self.fi.spawns.append((key, tname, node.lineno))

    # nested defs/lambdas are separate functions; the model links
    # them with a def-site call edge instead of inlining their body
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        nested = f"{self.fi.scope}.{node.name}"
        key = (self.fi.relpath, nested)
        if key in self.model.functions:
            self.fi.raw_calls.append(
                (("def-site", key), self.held, node.lineno))

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # nested class bodies are scanned via their methods


class ConcurrencyModel:
    """See module docstring.  Build with :func:`concurrency_model`
    (cached per AnalysisContext)."""

    def __init__(self, root: str, ctx):
        self.root = root
        self.ctx = ctx
        cg = ctx.callgraph
        self.cg = cg
        #: bare class name -> ClassModel (ambiguous names dropped)
        self.classes: dict[str, ClassModel] = {}
        #: (relpath, var) -> lock ctor name, for module-level locks
        self.module_locks: dict[tuple[str, str], str] = {}
        #: (relpath, scope) -> FuncInfo
        self.functions: dict[tuple[str, str], FuncInfo] = {}
        #: jit-root function keys (purity-walk dispatch roots)
        self.jit_roots: set[tuple[str, str]] = set()

        self._collect_classes_and_locks()
        self._scan_functions()
        self._resolve_edges()

    # -- pass 1: classes, class attrs, module locks ------------------------

    def _collect_classes_and_locks(self) -> None:
        ambiguous: set[str] = set()
        for rel in self.cg.files:
            mi = self.cg.module(rel)
            if mi is None:
                continue
            for node in mi.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    ctor = dotted_name(
                        node.value.func).split(".")[-1]
                    if ctor in _LOCK_CTORS:
                        self.module_locks[
                            (rel, node.targets[0].id)] = ctor
            for scope, cnode in self._iter_classes(mi.tree, ""):
                cm = ClassModel(cnode.name, rel, scope, cnode)
                if cnode.name in self.classes \
                        or cnode.name in ambiguous:
                    ambiguous.add(cnode.name)
                    self.classes.pop(cnode.name, None)
                    continue
                self.classes[cnode.name] = cm
        for cm in self.classes.values():
            self._scan_class_attrs(cm)

    @staticmethod
    def _iter_classes(tree: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(tree):
            if isinstance(child, ast.ClassDef):
                scope = f"{prefix}.{child.name}" if prefix \
                    else child.name
                yield scope, child
                yield from ConcurrencyModel._iter_classes(
                    child, scope)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                scope = f"{prefix}.{child.name}" if prefix \
                    else child.name
                yield from ConcurrencyModel._iter_classes(
                    child, scope)

    def _scan_class_attrs(self, cm: ClassModel) -> None:
        for item in cm.node.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                cm.methods.add(item.name)
                ann = {a.arg: _annotation_class(a.annotation)
                       for a in item.args.args}
                if item.name == "__init__":
                    cm.init_params = [
                        a.arg for a in item.args.args[1:]]
                    for sub in ast.walk(item):
                        if not (isinstance(sub, ast.Assign)
                                and len(sub.targets) == 1):
                            continue
                        attr = _self_attr(sub.targets[0])
                        if attr is None:
                            continue
                        v = sub.value
                        if isinstance(v, ast.BoolOp) and v.values:
                            v = v.values[0]
                        if isinstance(v, ast.Name):
                            cm.param_attrs.setdefault(
                                v.id, attr)
                for sub in ast.walk(item):
                    attr = None
                    value = None
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1:
                        attr = _self_attr(sub.targets[0])
                        value = sub.value
                    elif isinstance(sub, ast.AnnAssign):
                        attr = _self_attr(sub.target)
                        value = sub.value
                        t = _annotation_class(sub.annotation)
                        if attr and t:
                            cm.attr_types.setdefault(attr, t)
                    if attr is None or value is None:
                        continue
                    if isinstance(value, ast.Call):
                        ctor = dotted_name(
                            value.func).split(".")[-1]
                        if ctor in _LOCK_CTORS:
                            cm.locks.add(attr)
                            continue
                        qb = _queue_ctor_bound(value)
                        if qb is not None:
                            cm.queues[attr] = qb
                            continue
                        cname = dotted_name(value.func)
                        if cname and cname.split(".")[-1][:1] \
                                .isupper():
                            cm.attr_types.setdefault(
                                attr, cname.split(".")[-1])
                        elif cname and cname.split(".")[0][:1] \
                                .isupper():
                            # classmethod ctor: WAL.create(...)
                            cm.attr_types.setdefault(
                                attr, cname.split(".")[0])
                    elif isinstance(value, ast.Name) \
                            and ann.get(value.id):
                        # self.x = param  (annotated parameter)
                        cm.attr_types.setdefault(
                            attr, ann[value.id])

    # -- pass 2: per-function scans ----------------------------------------

    def _scan_functions(self) -> None:
        # create FuncInfo shells first (def-site edges need lookup)
        metas = []
        for rel in self.cg.files:
            mi = self.cg.module(rel)
            if mi is None:
                continue
            for scope, node in mi.functions.items():
                fi = FuncInfo(rel, scope, node)
                cls = self._enclosing_class(scope)
                if cls is not None:
                    fi.class_name = cls.name
                if any(_decorator_root(d)[0] for d in
                       getattr(node, "decorator_list", ())):
                    self.jit_roots.add((rel, scope))
                self.functions[(rel, scope)] = fi
                metas.append((fi, cls))
        for fi, cls in metas:
            self._type_params(fi, cls)
        # closure var-type inheritance: outer scopes scan first
        for fi, cls in sorted(metas,
                              key=lambda m: m[0].scope.count(".")):
            parent = fi.scope.rsplit(".", 1)[0] \
                if "." in fi.scope else None
            while parent:
                pfi = self.functions.get((fi.relpath, parent))
                if pfi is not None:
                    for k, v in pfi.var_types.items():
                        fi.var_types.setdefault(k, v)
                    for k, v in pfi.var_elem_types.items():
                        fi.var_elem_types.setdefault(k, v)
                    for k, v in pfi.local_queues.items():
                        fi.local_queues.setdefault(k, v)
                parent = parent.rsplit(".", 1)[0] \
                    if "." in parent else None
            scan = _FuncScan(self, fi, cls)
            for stmt in fi.node.body:
                scan.visit(stmt)

    def _enclosing_class(self, scope: str) -> ClassModel | None:
        if "." not in scope:
            return None
        cls_scope = scope.rsplit(".", 1)[0]
        bare = cls_scope.rsplit(".", 1)[-1]
        cm = self.classes.get(bare)
        if cm is not None and cm.scope == cls_scope:
            return cm
        return None

    def _type_params(self, fi: FuncInfo, cls) -> None:
        args = fi.node.args
        for a in (list(args.args) + list(args.kwonlyargs)
                  + list(getattr(args, "posonlyargs", []))):
            t = _annotation_class(a.annotation)
            if t in self.classes:
                fi.var_types[a.arg] = t

    # -- pass 3: resolve raw calls into function-key edges -----------------

    def resolve_name(self, relpath: str, name: str) -> list:
        """Project definitions a dotted call can reach (thin wrapper
        over the call graph, list of (rel, scope, node))."""
        return self.cg.resolve_call(relpath, name)

    def _method_key(self, cls: ClassModel | None, m: str):
        if cls is None or m not in cls.methods:
            return None
        key = (cls.relpath, f"{cls.scope}.{m}")
        return key if key in self.functions else None

    def _resolve_target(self, fi: FuncInfo, cls, target: ast.AST):
        """Thread-target expression -> function key, or None."""
        if isinstance(target, ast.Name):
            for rel, scope, _n in self.resolve_name(
                    fi.relpath, target.id):
                if (rel, scope) in self.functions:
                    return (rel, scope)
            # nested def in an enclosing scope
            probe = fi.scope
            while True:
                key = (fi.relpath, f"{probe}.{target.id}")
                if key in self.functions:
                    return key
                if "." not in probe:
                    break
                probe = probe.rsplit(".", 1)[0]
            return None
        attr = _self_attr(target)
        if attr is not None:
            return self._method_key(
                self._enclosing_class(fi.scope), attr)
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name):
            t = self.classes.get(
                fi.var_types.get(target.value.id, ""))
            if t is not None:
                return self._method_key(t, target.attr)
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Call):
            # Thread(target=Worker(...).run): bound method of a
            # freshly constructed instance
            parts = dotted_name(target.value.func).split(".")
            for cname in (parts[-1], parts[0]):
                if cname in self.classes:
                    return self._method_key(
                        self.classes[cname], target.attr)
        return None

    def _resolve_edges(self) -> None:
        spawn_targets = set()
        for fi in self.functions.values():
            for key, _n, _l in fi.spawns:
                spawn_targets.add(key)
        for key in spawn_targets:
            self.functions[key].is_spawn_target = True

        # (callee class, stored attr) -> {function keys} from ctor
        # callback-passing sites anywhere in the project
        callbacks: dict[tuple[str, str], set] = {}
        for fi in self.functions.values():
            cls = self.classes.get(fi.class_name)
            for cname, param, spec, _line in fi.ctor_callbacks:
                cm = self.classes[cname]
                attr = cm.param_attrs.get(param, param)
                tkeys = []
                if spec[0] == "self":
                    k = self._method_key(cls, spec[1])
                    if k:
                        tkeys.append(k)
                else:
                    for rel, scope, _n in self.resolve_name(
                            fi.relpath, spec[1]):
                        if (rel, scope) in self.functions:
                            tkeys.append((rel, scope))
                for k in tkeys:
                    callbacks.setdefault(
                        (cname, attr), set()).add(k)

        for fi in self.functions.values():
            cls = self.classes.get(fi.class_name)
            for raw, held, line in fi.raw_calls:
                kind = raw[0]
                keys = []
                if kind == "self":
                    k = self._method_key(cls, raw[1])
                    if k:
                        keys.append(k)
                    elif cls is not None:
                        # stored-callback invocation
                        keys.extend(callbacks.get(
                            (cls.name, raw[1]), ()))
                elif kind == "attr":
                    t = self.classes.get(
                        (cls.attr_types.get(raw[1], "")
                         if cls else ""))
                    k = self._method_key(t, raw[2])
                    if k:
                        keys.append(k)
                elif kind == "var":
                    t = self.classes.get(
                        fi.var_types.get(raw[1], ""))
                    k = self._method_key(t, raw[2])
                    if k:
                        keys.append(k)
                    elif t is None and len(raw) > 3 and raw[3]:
                        # module-receiver call (``rolemsg.pack(...)``)
                        for rel, scope, _n in self.resolve_name(
                                fi.relpath, raw[3]):
                            if (rel, scope) in self.functions:
                                keys.append((rel, scope))
                elif kind == "def-site":
                    keys.append(raw[1])
                else:  # dotted
                    for rel, scope, _n in self.resolve_name(
                            fi.relpath, raw[1]):
                        if (rel, scope) in self.functions:
                            keys.append((rel, scope))
                for k in keys:
                    if self.functions[k].is_spawn_target:
                        continue  # spawn boundary: no held carry
                    if k in self.jit_roots:
                        fi.blocking.append(
                            ("jit-dispatch",
                             f"{k[1]} (jit root)", held, line))
                    fi.edges.append((k, held, line))

    # -- derived: entry-held sets and transitive acquires ------------------

    def call_sites(self) -> dict:
        """callee key -> [(caller key, held_tuple, line)], callers
        inside ``__init__`` scopes excluded (single-threaded by
        construction)."""
        sites: dict[tuple, list] = {}
        for key, fi in self.functions.items():
            if fi.scope.split(".")[-1] == "__init__":
                continue
            for callee, held, line in fi.edges:
                sites.setdefault(callee, []).append(
                    (key, held, line))
        return sites

    def entry_held_intersection(self) -> dict:
        """Must-held-at-entry per function: the intersection over
        its non-construction call sites of (lexical held at the site
        + the caller's own entry set) — the cross-module form of the
        locks.py "call with lock held" convention."""
        sites = self.call_sites()
        universe = frozenset(self.all_lock_ids())
        entry = {key: (universe if key in sites else frozenset())
                 for key in self.functions}
        for _ in range(len(self.functions)):
            changed = False
            for key, slist in sites.items():
                v = None
                for caller, held, _line in slist:
                    s = frozenset(held) | entry[caller]
                    v = s if v is None else (v & s)
                v = v if v is not None else frozenset()
                if v != entry[key]:
                    entry[key] = v
                    changed = True
            if not changed:
                break
        return entry

    def entry_held_union(self, restrict: frozenset) -> dict:
        """May-held-at-entry per function, restricted to the given
        lock set (blocking-under-lock wants "reachable while held",
        a union over call sites)."""
        sites = self.call_sites()
        entry = {key: frozenset() for key in self.functions}
        for _ in range(len(self.functions)):
            changed = False
            for key, slist in sites.items():
                v = entry[key]
                for caller, held, _line in slist:
                    v = v | ((frozenset(held) | entry[caller])
                             & restrict)
                if v != entry[key]:
                    entry[key] = v
                    changed = True
            if not changed:
                break
        return entry

    def transitive_acquires(self) -> dict:
        """function key -> every lock id the call may acquire,
        through the resolved call edges."""
        acq = {key: frozenset(a for a, _h, _l in fi.acquires)
               for key, fi in self.functions.items()}
        for _ in range(32):
            changed = False
            for key, fi in self.functions.items():
                add = acq[key]
                for callee, _h, _l in fi.edges:
                    add = add | acq[callee]
                if add != acq[key]:
                    acq[key] = add
                    changed = True
            if not changed:
                break
        return acq

    def all_lock_ids(self) -> set[str]:
        out = {f"{rel}:{var}" for (rel, var) in self.module_locks}
        for cm in self.classes.values():
            out |= {f"{cm.name}.{a}" for a in cm.locks}
        return out


_model_lock = threading.Lock()


def concurrency_model(root: str, ctx) -> ConcurrencyModel:
    """The per-run model, built once and cached on the context
    (thread-safe: the parallel checker fan-out shares it)."""
    with _model_lock:
        m = getattr(ctx, "_concurrency_model", None)
        if m is None or m.root != root:
            m = ConcurrencyModel(root, ctx)
            ctx._concurrency_model = m
        return m
