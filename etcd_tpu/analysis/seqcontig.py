"""seq-contiguity: seq allocation and WAL-record construction must
stay adjacent.

The dist tier's WAL is one interleaved stream: every record carries
``index=self.seq`` and restart replay treats the sequence as dense —
a later seq landing on disk before an earlier one reads as an index
gap and fails recovery (the out-of-order-seq class the chaos drill
caught in distserver).  The code discipline that makes the bug
unrepresentable is *adjacency*: between ``self.seq += 1`` (the
allocation) and the first read of ``self.seq`` (the record
construction / WAL save that consumes it) nothing may run that can
interleave another allocator:

- ``yield`` / ``yield from`` / ``await`` — another coroutine or the
  consumer of a generator can allocate while this frame is parked;
- releasing a lock (``*.release()`` on a lock-ish receiver) — the
  very window the drill's kill-9 interleavings hit;
- *acquiring* a lock (a ``with <lock-ish>:`` entered, or
  ``*.acquire()``) — the allocation evidently happened OUTSIDE that
  lock, so another thread inside it can allocate in between.

Rule ``seq-gap`` flags each hazard sitting between an allocation and
its consuming read; rule ``seq-orphan`` flags an allocation that is
never read afterwards in the same function (a seq burned with no
record — a silent gap on disk).  Plain computation between the two
points is fine; so is holding a lock around the whole span (the
normal distserver shape, enforced separately by lock-discipline).
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, iter_functions

#: attribute spellings treated as THE sequence counter
_SEQ_ATTRS = {"seq"}


def _is_lockish(node: ast.AST) -> bool:
    """Heuristic: the receiver names a lock (``self.lock``,
    ``wal_lock``, ``self._mu``...)."""
    name = dotted_name(node)
    leaf = name.split(".")[-1].lower()
    return ("lock" in leaf or "mutex" in leaf or leaf == "mu"
            or leaf == "_mu")


def _pos(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0))


class SeqContiguityChecker(Checker):
    name = "seq-contiguity"
    targets = ("etcd_tpu/server/",)

    def check(self, relpath, tree, source, root=None, ctx=None):
        findings: list[Finding] = []
        for scope, fn in iter_functions(tree):
            self._check_function(relpath, scope, fn, findings)
        return findings

    @staticmethod
    def _walk_own(fn):
        """ast.walk minus nested function/lambda bodies (those are
        separate scopes with their own adjacency story, and
        iter_functions visits them on their own)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_function(self, relpath, scope, fn, findings) -> None:
        allocs: list[tuple[tuple[int, int], ast.AST]] = []
        reads: list[tuple[int, int]] = []
        hazards: list[tuple[tuple[int, int], str, ast.AST]] = []
        for node in self._walk_own(fn):
            if isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Attribute) \
                        and t.attr in _SEQ_ATTRS \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    allocs.append((_pos(node), node))
            elif isinstance(node, ast.Attribute) \
                    and node.attr in _SEQ_ATTRS \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and isinstance(node.ctx, ast.Load):
                reads.append(_pos(node))
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                hazards.append((_pos(node), "yield", node))
            elif isinstance(node, ast.Await):
                hazards.append((_pos(node), "await", node))
            elif isinstance(node, ast.AsyncFor):
                # iterating an async source suspends per item
                hazards.append((_pos(node), "await", node))
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                lockish = any(_is_lockish(item.context_expr)
                              for item in node.items)
                if lockish:
                    hazards.append(
                        (_pos(node), "lock-acquire", node))
                elif isinstance(node, ast.AsyncWith):
                    # __aenter__ suspends even on a non-lock manager
                    hazards.append((_pos(node), "await", node))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("release", "acquire") \
                    and _is_lockish(node.func.value):
                hazards.append(
                    (_pos(node), f"lock-{node.func.attr}", node))
        if not allocs:
            return
        reads.sort()
        hazards.sort(key=lambda h: h[0])
        allocs.sort(key=lambda a: a[0])
        alloc_positions = [a[0] for a in allocs]
        for i, (pos, alloc) in enumerate(allocs):
            # the protected span runs until the NEXT allocation (or
            # the function end): every read in it consumes THIS seq
            # value, so a hazard before the LAST such read is a gap —
            # an incidental early read (logging) must not mask a
            # hazard sitting before the real record construction
            end = (alloc_positions[i + 1]
                   if i + 1 < len(allocs) else (1 << 60, 0))
            span_reads = [r for r in reads if pos < r < end]
            if not span_reads:
                findings.append(Finding(
                    checker=self.name, path=relpath,
                    line=alloc.lineno, rule="seq-orphan",
                    scope=scope,
                    message=("`self.seq += 1` allocates a sequence "
                             "number that is never written to a WAL "
                             "record in this function — a silent "
                             "index gap on restart replay"),
                    detail="seq-orphan"))
                continue
            last_read = span_reads[-1]
            for hpos, kind, hnode in hazards:
                if pos < hpos < last_read:
                    findings.append(Finding(
                        checker=self.name, path=relpath,
                        line=hnode.lineno, rule="seq-gap",
                        scope=scope,
                        message=(
                            f"`{kind}` between `self.seq += 1` "
                            f"(line {alloc.lineno}) and the record "
                            f"construction that consumes it — "
                            f"another allocator can interleave and "
                            f"a later seq lands on disk first "
                            f"(out-of-order-seq restart gap)"),
                        detail=kind))
