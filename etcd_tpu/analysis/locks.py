"""lock-discipline: acquisition-order cycles + unguarded writes.

The threaded tier (store + servers) uses plain ``threading`` locks
acquired with ``with self.<lock>:``.  This checker derives, per
class:

- the set of lock attributes (``self.x = threading.Lock()/RLock()``),
- for every method, which locks are held at each point — lexically
  (enclosing ``with``) plus at-entry (the **intersection** of locks
  held at every intra-class call site, the "call with lock held"
  convention made mechanical),
- the **lock-acquisition graph**: an edge ``A → B`` whenever ``B`` is
  acquired (directly or via a call, including calls through typed
  attributes like ``self.store`` → ``Store``) while ``A`` is held.

Findings:

- ``lock-cycle``: a cycle in the acquisition graph — two threads
  entering it from different ends deadlock.
- ``unguarded-write``: an attribute written under a lock somewhere
  but also written with **no** lock held outside construction
  (``__init__`` and helpers reachable only from it are exempt —
  single-threaded by construction).
"""

from __future__ import annotations

import ast
import os

from .engine import Checker, Finding, dotted_name

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    return dotted_name(node.func).split(".")[-1] in _LOCK_CTORS


def _self_attr(node: ast.AST) -> str | None:
    """'attr' for ``self.attr`` nodes, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, relpath: str, name: str, node: ast.ClassDef):
        self.relpath = relpath
        self.name = name
        self.node = node
        self.locks: set[str] = set()
        # attr -> class name (self.attr = ClassName(...) in __init__)
        self.attr_types: dict[str, str] = {}
        self.methods: dict[str, ast.FunctionDef] = {}
        # method -> list[(callee_method, held_set, line)]
        self.calls: dict[str, list] = {}
        # method -> list[(attr_name, callee_method, held_set, line)]
        self.attr_calls: dict[str, list] = {}
        # method -> list[(lock, held_set, line)]  (with-acquisitions)
        self.acquires: dict[str, list] = {}
        # method -> list[(attr, held_set, line)]  (self.attr writes)
        self.writes: dict[str, list] = {}
        # computed later
        self.entry_held: dict[str, frozenset] = {}
        self.excluded: set[str] = set()


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body tracking the lexical held set."""

    def __init__(self, ci: _ClassInfo, mname: str):
        self.ci = ci
        self.m = mname
        self.held: tuple[str, ...] = ()
        ci.calls.setdefault(mname, [])
        ci.attr_calls.setdefault(mname, [])
        ci.acquires.setdefault(mname, [])
        ci.writes.setdefault(mname, [])

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr and attr in self.ci.locks:
                self.ci.acquires[self.m].append(
                    (attr, frozenset(self.held), node.lineno))
                acquired.append(attr)
        prev = self.held
        self.held = prev + tuple(a for a in acquired
                                 if a not in prev)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    def _record_write(self, target: ast.AST, line: int) -> None:
        # self.attr = / self.attr[...] = / self.attr.sub = (outer
        # attr is the shared name a lock would guard)
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            attr = _self_attr(node)
            if attr is not None:
                if attr not in self.ci.locks:
                    self.ci.writes[self.m].append(
                        (attr, frozenset(self.held), line))
                return
            node = node.value

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    self._record_write(el, node.lineno)
            else:
                self._record_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            recv_attr = _self_attr(f.value)
            if isinstance(f.value, ast.Name) \
                    and f.value.id == "self":
                self.ci.calls[self.m].append(
                    (f.attr, frozenset(self.held), node.lineno))
            elif recv_attr is not None:
                # self.<attr>.<method>() — cross-class via attr type
                self.ci.attr_calls[self.m].append(
                    (recv_attr, f.attr, frozenset(self.held),
                     node.lineno))
        self.generic_visit(node)

    # nested defs inherit the held set of their definition site (the
    # common closure-callback pattern: defined and called under the
    # same lock); conservative but right for this tree
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _scan_class(relpath: str, node: ast.ClassDef) -> _ClassInfo:
    ci = _ClassInfo(relpath, node.name, node)
    for item in node.body:
        if isinstance(item, ast.FunctionDef):
            ci.methods[item.name] = item
    # pass 1: lock attrs + typed attrs from any method (usually
    # __init__)
    for mname, fn in ci.methods.items():
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                attr = _self_attr(sub.targets[0])
                if attr is None:
                    continue
                if _is_lock_ctor(sub.value):
                    ci.locks.add(attr)
                elif isinstance(sub.value, ast.Call):
                    cname = dotted_name(sub.value.func)
                    if cname and cname[:1].isupper():
                        ci.attr_types[attr] = cname.split(".")[-1]
    # pass 2: per-method scan
    for mname, fn in ci.methods.items():
        _MethodScan(ci, mname).visit(fn)
    return ci


def _compute_entry_and_exclusions(ci: _ClassInfo) -> None:
    # construction-only methods: __init__ + methods whose every
    # intra-class call site lives in an already-excluded method
    excluded = {"__init__"}
    changed = True
    while changed:
        changed = False
        for m in ci.methods:
            if m in excluded:
                continue
            sites = [caller for caller, calls in ci.calls.items()
                     for (callee, _h, _l) in calls if callee == m]
            if sites and all(s in excluded for s in sites):
                excluded.add(m)
                changed = True
    ci.excluded = excluded

    # entry-held fixpoint over non-construction call sites
    all_locks = frozenset(ci.locks)
    entry = {m: (all_locks if any(
        callee == m and caller not in excluded
        for caller, calls in ci.calls.items()
        for (callee, _h, _l) in calls) else frozenset())
        for m in ci.methods}
    for _ in range(len(ci.methods) + 2):
        changed = False
        nxt = dict(entry)
        for m in ci.methods:
            sites = []
            for caller, calls in ci.calls.items():
                if caller in excluded:
                    continue
                for (callee, held, _l) in calls:
                    if callee == m:
                        sites.append(held | entry[caller])
            if sites:
                v = frozenset.intersection(*map(frozenset, sites))
                if v != entry[m]:
                    nxt[m] = v
                    changed = True
        entry = nxt
        if not changed:
            break
    ci.entry_held = entry


def _transitive_acquires(classes: dict[str, _ClassInfo]
                         ) -> dict[tuple[str, str], frozenset]:
    """(class, method) → every lock (``Class.attr``) the call may
    acquire, through intra-class calls and typed-attribute calls."""
    acq: dict[tuple[str, str], frozenset] = {}
    for cname, ci in classes.items():
        for m in ci.methods:
            acq[(cname, m)] = frozenset(
                f"{cname}.{lock}" for (lock, _h, _l)
                in ci.acquires.get(m, ()))
    for _ in range(8):
        changed = False
        for cname, ci in classes.items():
            for m in ci.methods:
                cur = acq[(cname, m)]
                add = frozenset()
                for (callee, _h, _l) in ci.calls.get(m, ()):
                    add |= acq.get((cname, callee), frozenset())
                for (attr, callee, _h, _l) in \
                        ci.attr_calls.get(m, ()):
                    tcls = ci.attr_types.get(attr)
                    if tcls in classes:
                        add |= acq.get((tcls, callee), frozenset())
                if not add <= cur:
                    acq[(cname, m)] = cur | add
                    changed = True
        if not changed:
            break
    return acq


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    targets = (
        "etcd_tpu/store/store.py",
        "etcd_tpu/store/ttl_heap.py",
        "etcd_tpu/server/server.py",
        "etcd_tpu/server/multigroup.py",
        "etcd_tpu/server/distserver.py",
    )

    def __init__(self):
        self._cache: dict[str, dict[str, list[Finding]]] = {}

    def check(self, relpath, tree, source, root=None, ctx=None):
        root = root or os.getcwd()
        if root not in self._cache:
            self._cache[root] = self._analyze(root)
        return self._cache[root].get(relpath, [])

    # -- whole-target-set analysis ---------------------------------------

    def _analyze(self, root: str) -> dict[str, list[Finding]]:
        classes: dict[str, _ClassInfo] = {}
        for rel in self.targets:
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=rel)
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = _scan_class(rel, node)
                    _compute_entry_and_exclusions(ci)
                    classes[node.name] = ci

        by_file: dict[str, list[Finding]] = {}

        def emit(f: Finding) -> None:
            by_file.setdefault(f.path, []).append(f)

        # -- acquisition graph + cycles
        acq = _transitive_acquires(classes)
        edges: dict[str, set[str]] = {}
        edge_sites: dict[tuple[str, str], tuple[str, int, str]] = {}

        def add_edge(a: str, b: str, rel: str, line: int,
                     scope: str) -> None:
            if a == b:
                return  # RLock re-entry, not an ordering edge
            edges.setdefault(a, set()).add(b)
            edge_sites.setdefault((a, b), (rel, line, scope))

        for cname, ci in classes.items():
            for m in ci.methods:
                if m in ci.excluded:
                    continue
                base = ci.entry_held.get(m, frozenset())
                for (lock, held, line) in ci.acquires.get(m, ()):
                    for h in held | base:
                        add_edge(f"{cname}.{h}", f"{cname}.{lock}",
                                 ci.relpath, line, f"{cname}.{m}")
                for (callee, held, line) in ci.calls.get(m, ()):
                    tgt = acq.get((cname, callee), frozenset())
                    for h in held | base:
                        for t in tgt:
                            add_edge(f"{cname}.{h}", t,
                                     ci.relpath, line,
                                     f"{cname}.{m}")
                for (attr, callee, held, line) in \
                        ci.attr_calls.get(m, ()):
                    tcls = ci.attr_types.get(attr)
                    if tcls not in classes:
                        continue
                    tgt = acq.get((tcls, callee), frozenset())
                    for h in held | base:
                        for t in tgt:
                            add_edge(f"{cname}.{h}", t,
                                     ci.relpath, line,
                                     f"{cname}.{m}")

        for cyc in self._cycles(edges):
            a, b = cyc[0], cyc[1 % len(cyc)]
            rel, line, scope = edge_sites.get(
                (a, b), (next(iter(classes.values())).relpath, 1, a))
            emit(Finding(
                checker=self.name, path=rel, line=line,
                rule="lock-cycle", scope=scope,
                message=("lock acquisition cycle: "
                         + " -> ".join(cyc + [cyc[0]])
                         + " — two threads entering from different "
                           "ends deadlock"),
                detail="->".join(sorted(cyc))))

        # -- unguarded writes
        for cname, ci in classes.items():
            if not ci.locks:
                continue
            sites: dict[str, list] = {}
            for m in ci.methods:
                if m in ci.excluded:
                    continue
                base = ci.entry_held.get(m, frozenset())
                for (attr, held, line) in ci.writes.get(m, ()):
                    sites.setdefault(attr, []).append(
                        (m, held | base, line))
            for attr, ws in sites.items():
                locked = [w for w in ws if w[1]]
                bare = [w for w in ws if not w[1]]
                if locked and bare:
                    for (m, _h, line) in bare:
                        emit(Finding(
                            checker=self.name, path=ci.relpath,
                            line=line, rule="unguarded-write",
                            scope=f"{cname}.{m}",
                            message=(
                                f"`self.{attr}` is written under a "
                                f"lock in {len(locked)} other "
                                f"site(s) but written here with no "
                                f"lock held"),
                            detail=attr))
        return by_file

    @staticmethod
    def _cycles(edges: dict[str, set[str]]) -> list[list[str]]:
        """Small-graph cycle enumeration (unique by node set)."""
        out: list[list[str]] = []
        seen_sets: set[frozenset] = set()

        def dfs(start, node, path, visiting):
            for nxt in sorted(edges.get(node, ())):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(list(path))
                elif nxt not in visiting and len(path) < 6:
                    visiting.add(nxt)
                    dfs(start, nxt, path + [nxt], visiting)
                    visiting.discard(nxt)

        for start in sorted(edges):
            dfs(start, start, [start], {start})
        return out
