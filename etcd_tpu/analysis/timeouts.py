"""timeout-bands: election/heartbeat/member-count band invariants.

DistMember's stratified election bands carve ``m`` disjoint
width->=1 bands out of ``[election, 2*election)`` — impossible when
``election < m``, which is why the constructor clamps ``election =
max(election, m)`` (PR 1).  A clamp protects the process but hides
the misconfiguration: the operator asked for a 4-tick election on an
8-host cluster and silently got 8.  This checker lifts the invariant
to every *config surface* so the bad number is caught where it is
written down:

- ``election-band``: a construction call (``DistMember`` /
  ``MultiRaft`` / ``init_groups`` / ``DistServer``) whose member
  count and election ticks are both statically known with
  ``election < m``.  ``DistServer``'s ``m`` is ``len(peer_urls)``
  when the list is a literal; omitted ``election`` uses the callee's
  known default.
- ``heartbeat-band``: classic-tier ``Raft`` / ``start_node`` /
  ``restart_node`` calls with constant ``heartbeat >= election`` —
  a leader that beats slower than followers time out can never hold
  leadership (raft.go invariant).
- ``cli-band``: in an argparse surface, an ``--*election*`` flag
  whose literal default is smaller than a ``--*members*`` flag's
  default in the same module, or a non-positive election default —
  the CLI is a config surface too, and its defaults are the most
  widely deployed config of all.

Dynamic values stay quiet (the runtime clamp still covers them);
this checker exists so constants written in code and flag tables
obey the band *before* the clamp rewrites them.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map


def _const_int(node: ast.AST | None) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _arg(call: ast.Call, pos: int | None, kw: str):
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if pos is not None and pos < len(call.args):
        return call.args[pos]
    return None


#: callee leaf name -> (m positional index, election positional
#: index, election default).  Positions track the real signatures:
#: DistMember(g, m, slot, cap, election=10),
#: MultiRaft(g, m, cap, election=10),
#: init_groups(g, m, cap, election=10).
_ELECTION_CTORS = {
    "DistMember": (1, 4, 10),
    "MultiRaft": (1, 3, 10),
    "init_groups": (1, 3, 10),
}

#: classic tier: (election positional index, heartbeat positional
#: index) — Raft(id, peers, election, heartbeat),
#: start_node(id, peers, election, heartbeat),
#: restart_node(id, election, heartbeat, ...)
_HEARTBEAT_CTORS = {
    "Raft": (2, 3),
    "start_node": (2, 3),
    "restart_node": (1, 2),
}


class TimeoutBandChecker(Checker):
    name = "timeout-bands"
    targets = ("etcd_tpu/", "scripts/", "bench.py")

    def check(self, relpath, tree, source, root=None, ctx=None):
        findings: list[Finding] = []
        scopes = scope_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted_name(node.func).split(".")[-1]
            if leaf in _ELECTION_CTORS:
                self._check_election(relpath, scopes.get(node, ""),
                                     leaf, node, findings)
            elif leaf == "DistServer":
                self._check_distserver(relpath,
                                       scopes.get(node, ""), node,
                                       findings)
            elif leaf in _HEARTBEAT_CTORS:
                self._check_heartbeat(relpath,
                                      scopes.get(node, ""), leaf,
                                      node, findings)
        self._check_argparse(relpath, tree, scopes, findings)
        return findings

    def _check_election(self, relpath, scope, leaf, call,
                        findings) -> None:
        # DistMember is the engine seam: g is positional, m may be
        # positional or keyword
        m_pos, e_pos, e_default = _ELECTION_CTORS[leaf]
        m = _const_int(_arg(call, m_pos, "m"))
        e_node = _arg(call, e_pos, "election")
        e = _const_int(e_node) if e_node is not None else e_default
        if m is None or e is None:
            return
        if e < m:
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="election-band", scope=scope,
                message=(
                    f"`{leaf}(... m={m}, election={e})`: "
                    f"{m} disjoint election bands cannot fit in "
                    f"[{e}, {2 * e}) — the runtime clamps election "
                    f"up to {m}, so this config lies about its "
                    f"recovery bound; pass election >= m"),
                detail=f"{leaf}:m>{e}"))

    def _check_distserver(self, relpath, scope, call,
                          findings) -> None:
        peers = _arg(call, None, "peer_urls")
        if not isinstance(peers, (ast.List, ast.Tuple)):
            return
        m = len(peers.elts)
        e_node = _arg(call, None, "election")
        e = _const_int(e_node) if e_node is not None else 10
        if e is None or m == 0:
            return
        if e < m:
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="election-band", scope=scope,
                message=(
                    f"`DistServer(... peer_urls=<{m} hosts>, "
                    f"election={e})`: {m} disjoint election bands "
                    f"cannot fit in [{e}, {2 * e}) — pass "
                    f"election >= len(peer_urls)"),
                detail=f"DistServer:m>{e}"))

    def _check_heartbeat(self, relpath, scope, leaf, call,
                         findings) -> None:
        e_pos, h_pos = _HEARTBEAT_CTORS[leaf]
        e = _const_int(_arg(call, e_pos, "election"))
        h = _const_int(_arg(call, h_pos, "heartbeat"))
        if e is None or h is None:
            return
        if h >= e:
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="heartbeat-band", scope=scope,
                message=(
                    f"`{leaf}(... election={e}, heartbeat={h})`: "
                    f"the heartbeat interval must be strictly "
                    f"below the election timeout or followers "
                    f"campaign against a healthy leader"),
                detail=f"{leaf}:hb>={h}"))

    def _check_argparse(self, relpath, tree, scopes,
                        findings) -> None:
        election: list[tuple[str, int, ast.Call]] = []
        members: list[tuple[str, int]] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            flag = node.args[0].value
            default = _const_int(_arg(node, None, "default"))
            if default is None:
                continue
            if "election" in flag:
                election.append((flag, default, node))
            elif "members" in flag:
                members.append((flag, default))
        for flag, default, node in election:
            scope = scopes.get(node, "")
            if default <= 0:
                findings.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="cli-band", scope=scope,
                    message=(f"`{flag}` default {default} is not a "
                             f"positive tick count"),
                    detail=f"{flag}:nonpos"))
                continue
            for mflag, mdefault in members:
                if default < mdefault:
                    findings.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="cli-band",
                        scope=scope,
                        message=(
                            f"`{flag}` default {default} is below "
                            f"`{mflag}` default {mdefault}: "
                            f"{mdefault} member election bands "
                            f"cannot fit in [{default}, "
                            f"{2 * default}) — raise the election "
                            f"default to at least the member "
                            f"default"),
                        detail=f"{flag}<{mflag}"))

