"""timeout-bands: election/heartbeat/member-count band invariants.

DistMember's stratified election bands carve ``m`` disjoint
width->=1 bands out of ``[election, 2*election)`` — impossible when
``election < m``, which is why the constructor clamps ``election =
max(election, m)`` (PR 1).  A clamp protects the process but hides
the misconfiguration: the operator asked for a 4-tick election on an
8-host cluster and silently got 8.  This checker lifts the invariant
to every *config surface* so the bad number is caught where it is
written down:

- ``election-band``: a construction call (``DistMember`` /
  ``MultiRaft`` / ``init_groups`` / ``DistServer``) whose member
  count and election ticks are both statically known with
  ``election < m``.  ``DistServer``'s ``m`` is ``len(peer_urls)``
  when the list is a literal; omitted ``election`` uses the callee's
  known default.
- ``heartbeat-band``: classic-tier ``Raft`` / ``start_node`` /
  ``restart_node`` calls with constant ``heartbeat >= election`` —
  a leader that beats slower than followers time out can never hold
  leadership (raft.go invariant).
- ``cli-band``: in an argparse surface, an ``--*election*`` flag
  whose literal default is smaller than a ``--*members*`` flag's
  default in the same module, or a non-positive election default —
  the CLI is a config surface too, and its defaults are the most
  widely deployed config of all.
- ``lease-band`` (PR 7): a leader lease may only vouch for reads
  while no quorum-heard follower can have fired its election timer,
  so ``lease_ticks < election − drift`` (drift = ``max(1,
  election // 10)``, the clock-drift margin) at every surface: a
  ``DistServer`` call with literal ``lease_ticks`` and a known
  election, and an argparse ``--*lease*`` default against the
  ``--*election*`` default in the same module.  A lease at or past
  the band is a linearizability violation waiting for a partition —
  a new leader can commit while the stale lease still serves.
  ``lease_ticks <= 0`` (lease disabled / auto) stays quiet.

Dynamic values stay quiet (the runtime clamp still covers them);
this checker exists so constants written in code and flag tables
obey the band *before* the clamp rewrites them.
"""

from __future__ import annotations

import ast

from .engine import Checker, Finding, dotted_name, scope_map


def _const_int(node: ast.AST | None) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _arg(call: ast.Call, pos: int | None, kw: str):
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if pos is not None and pos < len(call.args):
        return call.args[pos]
    return None


#: callee leaf name -> (m positional index, election positional
#: index, election default).  Positions track the real signatures:
#: DistMember(g, m, slot, cap, election=10),
#: MultiRaft(g, m, cap, election=10),
#: init_groups(g, m, cap, election=10).
_ELECTION_CTORS = {
    "DistMember": (1, 4, 10),
    "MultiRaft": (1, 3, 10),
    "init_groups": (1, 3, 10),
}

def _lease_drift(election: int) -> int:
    """The lease band's clock-drift margin in ticks.  This package
    is stdlib-only, so this is a COPY of the runtime's formula
    (server/readindex.py:lease_drift_ticks) — pinned equal by
    tests/test_analysis.py's drift-guard so the static band and the
    runtime validation can never disagree."""
    return max(1, election // 10)


#: classic tier: (election positional index, heartbeat positional
#: index) — Raft(id, peers, election, heartbeat),
#: start_node(id, peers, election, heartbeat),
#: restart_node(id, election, heartbeat, ...)
_HEARTBEAT_CTORS = {
    "Raft": (2, 3),
    "start_node": (2, 3),
    "restart_node": (1, 2),
}


class TimeoutBandChecker(Checker):
    name = "timeout-bands"
    targets = ("etcd_tpu/", "scripts/", "bench.py")

    def check(self, relpath, tree, source, root=None, ctx=None):
        findings: list[Finding] = []
        scopes = scope_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted_name(node.func).split(".")[-1]
            if leaf in _ELECTION_CTORS:
                self._check_election(relpath, scopes.get(node, ""),
                                     leaf, node, findings)
            elif leaf == "DistServer":
                self._check_distserver(relpath,
                                       scopes.get(node, ""), node,
                                       findings)
            elif leaf in _HEARTBEAT_CTORS:
                self._check_heartbeat(relpath,
                                      scopes.get(node, ""), leaf,
                                      node, findings)
        self._check_argparse(relpath, tree, scopes, findings)
        return findings

    def _check_election(self, relpath, scope, leaf, call,
                        findings) -> None:
        # DistMember is the engine seam: g is positional, m may be
        # positional or keyword
        m_pos, e_pos, e_default = _ELECTION_CTORS[leaf]
        m = _const_int(_arg(call, m_pos, "m"))
        e_node = _arg(call, e_pos, "election")
        e = _const_int(e_node) if e_node is not None else e_default
        if m is None or e is None:
            return
        if e < m:
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="election-band", scope=scope,
                message=(
                    f"`{leaf}(... m={m}, election={e})`: "
                    f"{m} disjoint election bands cannot fit in "
                    f"[{e}, {2 * e}) — the runtime clamps election "
                    f"up to {m}, so this config lies about its "
                    f"recovery bound; pass election >= m"),
                detail=f"{leaf}:m>{e}"))

    def _check_distserver(self, relpath, scope, call,
                          findings) -> None:
        peers = _arg(call, None, "peer_urls")
        m = (len(peers.elts)
             if isinstance(peers, (ast.List, ast.Tuple)) else None)
        e_node = _arg(call, None, "election")
        e = _const_int(e_node) if e_node is not None else 10
        if e is not None and m:
            if e < m:
                findings.append(Finding(
                    checker=self.name, path=relpath,
                    line=call.lineno,
                    rule="election-band", scope=scope,
                    message=(
                        f"`DistServer(... peer_urls=<{m} hosts>, "
                        f"election={e})`: {m} disjoint election "
                        f"bands cannot fit in [{e}, {2 * e}) — pass "
                        f"election >= len(peer_urls)"),
                    detail=f"DistServer:m>{e}"))
        # lease-band (PR 7): only when lease_ticks is an explicit
        # literal (the omitted default, election//2, always sits in
        # band; <= 0 disables the lease).  election must be known
        # too — the constructor clamps election up to m, so use the
        # clamped value when the peer list is literal.
        lease = _const_int(_arg(call, None, "lease_ticks"))
        if lease is None or lease <= 0 or e is None or not m:
            # dynamic values stay quiet — the runtime validation
            # (DistServer.__init__ raises) still covers them
            return
        e_eff = max(e, m)
        if lease >= e_eff - _lease_drift(e_eff):
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="lease-band", scope=scope,
                message=(
                    f"`DistServer(... election={e}, "
                    f"lease_ticks={lease})`: the lease must sit "
                    f"strictly below election - drift = {e_eff} - "
                    f"{_lease_drift(e_eff)} ticks, or a stale "
                    f"lease can serve reads after a new leader "
                    f"commits (linearizability violation under "
                    f"partition)"),
                detail=f"DistServer:lease>={lease}"))

    def _check_heartbeat(self, relpath, scope, leaf, call,
                         findings) -> None:
        e_pos, h_pos = _HEARTBEAT_CTORS[leaf]
        e = _const_int(_arg(call, e_pos, "election"))
        h = _const_int(_arg(call, h_pos, "heartbeat"))
        if e is None or h is None:
            return
        if h >= e:
            findings.append(Finding(
                checker=self.name, path=relpath, line=call.lineno,
                rule="heartbeat-band", scope=scope,
                message=(
                    f"`{leaf}(... election={e}, heartbeat={h})`: "
                    f"the heartbeat interval must be strictly "
                    f"below the election timeout or followers "
                    f"campaign against a healthy leader"),
                detail=f"{leaf}:hb>={h}"))

    def _check_argparse(self, relpath, tree, scopes,
                        findings) -> None:
        election: list[tuple[str, int, ast.Call]] = []
        members: list[tuple[str, int]] = []
        leases: list[tuple[str, int, ast.Call]] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            flag = node.args[0].value
            default = _const_int(_arg(node, None, "default"))
            if default is None:
                continue
            if "election" in flag:
                election.append((flag, default, node))
            elif "members" in flag:
                members.append((flag, default))
            elif "lease" in flag:
                leases.append((flag, default, node))
        # lease-band on flag tables: a --*lease* default must clear
        # the --*election* default's band in the same module
        # (<= 0 = lease disabled/auto, quiet)
        for lflag, ldefault, lnode in leases:
            if ldefault <= 0:
                continue
            for eflag, edefault, _enode in election:
                if edefault <= 0:
                    continue
                if ldefault >= edefault - _lease_drift(edefault):
                    findings.append(Finding(
                        checker=self.name, path=relpath,
                        line=lnode.lineno, rule="lease-band",
                        scope=scopes.get(lnode, ""),
                        message=(
                            f"`{lflag}` default {ldefault} is not "
                            f"strictly below `{eflag}` default "
                            f"{edefault} minus the "
                            f"{_lease_drift(edefault)}-tick drift "
                            f"margin — a stale lease could serve "
                            f"reads after a new leader commits; "
                            f"lower the lease default"),
                        detail=f"{lflag}>={ldefault}"))
        for flag, default, node in election:
            scope = scopes.get(node, "")
            if default <= 0:
                findings.append(Finding(
                    checker=self.name, path=relpath,
                    line=node.lineno, rule="cli-band", scope=scope,
                    message=(f"`{flag}` default {default} is not a "
                             f"positive tick count"),
                    detail=f"{flag}:nonpos"))
                continue
            for mflag, mdefault in members:
                if default < mdefault:
                    findings.append(Finding(
                        checker=self.name, path=relpath,
                        line=node.lineno, rule="cli-band",
                        scope=scope,
                        message=(
                            f"`{flag}` default {default} is below "
                            f"`{mflag}` default {mdefault}: "
                            f"{mdefault} member election bands "
                            f"cannot fit in [{default}, "
                            f"{2 * default}) — raise the election "
                            f"default to at least the member "
                            f"default"),
                        detail=f"{flag}<{mflag}"))

