"""Visitor engine + finding/baseline plumbing shared by the checkers.

Design points:

- One parsed AST per file per run (checkers share the cache).
- Findings carry a **stable fingerprint** (checker, file, enclosing
  scope, rule, detail — never the line number) so routine edits above
  a legacy finding don't churn the baseline.
- The baseline is a committed JSON file mapping fingerprint →
  metadata + a one-line human justification.  ``scripts/lint
  --baseline`` refreshes it; a finding whose fingerprint is absent
  fails the gate.
- ``# lint: ok(<checker>)`` on the flagged line is an inline
  suppression for cases where a comment at the site beats a baseline
  entry.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field


@dataclass
class Finding:
    checker: str          # checker name ("tracer-purity", ...)
    path: str             # repo-relative posix path
    line: int             # 1-based line (display only, not identity)
    rule: str             # short rule id ("host-cast", "lock-cycle")
    scope: str            # enclosing Class.function ("" = module)
    message: str          # human sentence
    detail: str = ""      # small stable token (attr/call name)

    @property
    def fingerprint(self) -> str:
        key = "|".join((self.checker, self.path, self.scope,
                        self.rule, self.detail))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/"
                f"{self.rule}] {self.message}"
                f"  (fingerprint {self.fingerprint})")


class AnalysisContext:
    """Shared per-run state: ONE parsed AST per file (checkers and
    the call graph read the same cache), plus the lazily-built
    whole-program :class:`~.callgraph.CallGraph`.  It dies with the
    run, so stale-root leaks between fixture trees are
    impossible."""

    def __init__(self, root: str):
        self.root = root
        self._cache: dict[str, tuple[ast.AST, str]] = {}
        self._lines: dict[str, list[str]] = {}
        self._cg = None
        self._parse_lock = threading.Lock()

    def parse(self, relpath: str) -> tuple[ast.AST, str]:
        # lock-free on the hot path; checkers run on a thread pool
        # and may miss concurrently (whole-tree checkers parse files
        # outside the run's selection), so misses serialize
        hit = self._cache.get(relpath)
        if hit is None:
            with self._parse_lock:
                hit = self._cache.get(relpath)
                if hit is None:
                    path = os.path.join(self.root, relpath)
                    with open(path) as fh:
                        source = fh.read()
                    hit = (ast.parse(source, filename=relpath),
                           source)
                    self._cache[relpath] = hit
        return hit

    def lines(self, relpath: str) -> list[str]:
        hit = self._lines.get(relpath)
        if hit is None:
            try:
                hit = self.parse(relpath)[1].splitlines()
            except (OSError, SyntaxError):
                hit = []
            self._lines[relpath] = hit
        return hit

    @property
    def callgraph(self):
        if self._cg is None:
            from .callgraph import CallGraph

            self._cg = CallGraph(self.root, self.parse)
        return self._cg


class Checker:
    """One registered analysis.  Subclasses set ``name`` and
    ``targets`` (repo-relative paths or ``dir/`` prefixes) and
    implement ``check``.  ``ctx`` is the run's
    :class:`AnalysisContext`; cross-module checkers query
    ``ctx.callgraph``."""

    name = "base"
    targets: tuple[str, ...] = ()

    def wants(self, relpath: str) -> bool:
        for t in self.targets:
            if relpath == t or (t.endswith("/")
                                and relpath.startswith(t)):
                return True
        return False

    def check(self, relpath: str, tree: ast.AST, source: str,
              root: str | None = None, ctx: AnalysisContext | None
              = None) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class Baseline:
    """Accepted legacy findings: fingerprint → entry with a one-line
    ``justification`` (required — the gate rejects a baseline entry
    without one)."""

    entries: dict[str, dict] = field(default_factory=dict)

    def accepts(self, f: Finding) -> bool:
        return f.fingerprint in self.entries

    def unjustified(self) -> list[str]:
        return [fp for fp, e in sorted(self.entries.items())
                if not str(e.get("justification", "")).strip()
                or str(e.get("justification", "")).startswith("TODO")]


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline()
    with open(path) as f:
        doc = json.load(f)
    return Baseline(entries=doc.get("entries", {}))


def save_baseline(path: str, findings: list[Finding],
                  prior: Baseline) -> Baseline:
    """Write the current findings as the accepted baseline, keeping
    prior justifications for fingerprints that still fire; new
    entries get a TODO the author must replace (the gate and the
    tier-1 test both reject TODO justifications)."""
    entries: dict[str, dict] = {}
    for f in findings:
        old = prior.entries.get(f.fingerprint, {})
        entries[f.fingerprint] = {
            "checker": f.checker,
            "path": f.path,
            "rule": f.rule,
            "scope": f.scope,
            "detail": f.detail,
            "message": f.message,
            "justification": old.get("justification",
                                     "TODO: justify or fix"),
        }
    doc = {"version": 1, "entries": dict(sorted(entries.items()))}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return Baseline(entries=entries)


def prune_baseline(path: str, findings: list[Finding],
                   prior: Baseline) -> list[str]:
    """Drop baseline entries whose fingerprints no longer fire
    (keeping live entries' justifications verbatim) and rewrite the
    file.  Returns the pruned fingerprints, sorted."""
    live = {f.fingerprint for f in findings}
    stale = sorted(set(prior.entries) - live)
    if not stale:
        return []
    entries = {fp: e for fp, e in prior.entries.items()
               if fp in live}
    doc = {"version": 1, "entries": dict(sorted(entries.items()))}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    prior.entries = entries
    return stale


def _suppressed(source_lines: list[str], f: Finding) -> bool:
    if not (1 <= f.line <= len(source_lines)):
        return False
    return f"lint: ok({f.checker})" in source_lines[f.line - 1]


def target_files(root: str, checkers) -> dict[str, list]:
    """relpath -> [checkers wanting it], expanded from each
    checker's ``targets`` (``dir/`` prefixes walked)."""
    wanted: dict[str, list] = {}
    for c in checkers:
        for t in c.targets:
            if t.endswith("/"):
                base = os.path.join(root, t)
                for dirpath, dirs, files in os.walk(base):
                    dirs[:] = [d for d in dirs
                               if d != "__pycache__"]
                    for fn in files:
                        if not fn.endswith(".py"):
                            continue
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root)
                        rel = rel.replace(os.sep, "/")
                        wanted.setdefault(rel, []).append(c)
            else:
                if os.path.exists(os.path.join(root, t)):
                    wanted.setdefault(t, []).append(c)
    return wanted


def _record_run_metrics(checkers, findings: list[Finding],
                        seconds: float,
                        timings: dict[str, float] | None = None
                        ) -> None:
    """Publish the run summary through the obs registry (CATALOG
    families ``etcd_lint_findings{checker}`` /
    ``etcd_lint_run_seconds{checker}``) — best-effort; analysis must
    keep working even if the obs package is mid-refactor.  Wall time
    is labeled per checker (fan-out means they overlap; the
    ``_total`` child is the run's actual elapsed time, not the
    sum)."""
    try:
        from ..obs.metrics import registry
    except Exception:  # pragma: no cover - bootstrap order
        return
    per: dict[str, int] = {}
    for f in findings:
        per[f.checker] = per.get(f.checker, 0) + 1
    for c in checkers:
        registry.gauge("etcd_lint_findings", checker=c.name).set(
            per.get(c.name, 0))
    for name, secs in (timings or {}).items():
        registry.gauge("etcd_lint_run_seconds",
                       checker=name).set(secs)
    registry.gauge("etcd_lint_run_seconds",
                   checker="_total").set(seconds)


def run_checkers(root: str, checkers,
                 paths: list[str] | None = None,
                 ctx: AnalysisContext | None = None,
                 jobs: int | None = None) -> list[Finding]:
    """Run every checker over its target files under ``root``.
    ``paths`` restricts the run (repo-relative; ``./``-prefixes are
    normalized, and a path that selects no target file raises — a
    silent zero-findings pass on a typo'd path would read as
    clean).  Returns findings sorted by (path, line), inline
    suppressions already dropped; the run summary lands in the obs
    registry (``etcd_lint_findings``/``etcd_lint_run_seconds``).

    Checkers fan out over a thread pool (``jobs`` caps the width;
    default one thread per checker up to the CPU count).  They share
    ONE context: the AST cache is pre-filled serially below, and the
    call graph / concurrency model guard their lazy builds with
    their own locks, so the per-checker work is read-mostly."""
    t0 = time.monotonic()
    if paths is not None:
        paths = [os.path.normpath(p).replace(os.sep, "/")
                 for p in paths]
    ctx = ctx if ctx is not None else AnalysisContext(root)
    wanted = target_files(root, checkers)

    if paths is not None:
        unknown = [p for p in paths if p not in wanted]
        if unknown:
            raise ValueError(
                f"path(s) select no analysis target: {unknown} "
                f"(targets are repo-relative, e.g. "
                f"etcd_tpu/wal/wal.py)")

    selected = [rel for rel in sorted(wanted)
                if paths is None or rel in paths]
    for rel in selected:
        ctx.parse(rel)

    def run_one(c) -> tuple[list[Finding], float]:
        ct0 = time.monotonic()
        out: list[Finding] = []
        for rel in selected:
            if c not in wanted[rel]:
                continue
            tree, source = ctx.parse(rel)
            out.extend(c.check(rel, tree, source, root=root,
                               ctx=ctx))
        return out, time.monotonic() - ct0

    width = max(1, min(len(checkers), jobs if jobs is not None
                       else (os.cpu_count() or 4)))
    timings: dict[str, float] = {}
    findings: list[Finding] = []
    seen: set[tuple[str, int]] = set()
    with ThreadPoolExecutor(max_workers=width) as pool:
        # ex.map keeps registration order, so the dedup pass below
        # is deterministic regardless of completion order
        for c, (out, secs) in zip(checkers,
                                  pool.map(run_one, checkers)):
            timings[c.name] = secs
            for f in out:
                # cross-module checkers may flag a file other than
                # the one being checked — suppression comments are
                # honored at the FLAGGED site, and a finding reached
                # via two different entry files counts once
                lines = ctx.lines(f.path)
                key = (f.fingerprint, f.line)
                if key not in seen and not _suppressed(lines, f):
                    seen.add(key)
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _record_run_metrics(checkers, findings,
                        time.monotonic() - t0, timings)
    return findings


# -- small shared AST helpers -------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, "" otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def scope_map(tree: ast.AST) -> dict[ast.AST, str]:
    """node -> enclosing ``Class.function`` scope ("" = module) for
    every node in the module (deepest function wins)."""
    owner: dict[ast.AST, str] = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                scope = f"{prefix}.{child.name}" if prefix \
                    else child.name
                # plain assignment: inner functions are walked after
                # their enclosing one, so the DEEPEST scope wins —
                # scope feeds the finding fingerprint, so this must
                # match the pre-consolidation per-checker behavior
                for n in ast.walk(child):
                    owner[n] = scope
                walk(child, scope)
            elif isinstance(child, ast.ClassDef):
                name = f"{prefix}.{child.name}" if prefix \
                    else child.name
                walk(child, name)
            else:
                walk(child, prefix)

    walk(tree, "")
    return owner


def iter_functions(tree: ast.AST):
    """Yield (scope, node) for every function/method in the module;
    scope is ``Class.name`` or ``name`` (nested: ``outer.inner``)."""

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                scope = f"{prefix}.{child.name}" if prefix \
                    else child.name
                yield scope, child
                yield from walk(child, scope)
            elif isinstance(child, ast.ClassDef):
                name = f"{prefix}.{child.name}" if prefix \
                    else child.name
                yield from walk(child, name)
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")
