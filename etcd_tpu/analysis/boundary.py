"""device-boundary: per-round host materialization of jitted results.

The 24x TPU restart-replay regression (round-5 VERDICT) was a
transfer-per-round tax: a host fetch (``np.asarray``) of a value a
jitted call had just produced, sitting inside a per-round Python loop
— every iteration pays a full dispatch + D2H round trip that batching
(or keeping the value device-resident across rounds) would amortize.
``obs/devledger.py`` makes the tax *readable* at runtime on the
instrumented seams; this checker catches the pattern statically on
the un-instrumented ones (the ROADMAP open idea).

Flagged (rule ``per-round-fetch``): inside any ``for``/``while``
body, ``np.asarray(...)`` / ``np.array(...)`` whose argument is a
call to a jit-rooted function — or a name assigned from one inside
the same loop.  Jit roots are resolved in the module itself
(``@jax.jit`` / ``functools.partial(jax.jit, ...)`` decorators,
``f = jax.jit(g)`` bindings) and across ``from X import y`` edges
when X lives in this repo, so the common split (kernels in ``ops/``,
loops in ``server/``/``bench.py``) is covered.  Method calls on
engine objects (``mr.propose(...)``) are NOT resolved — that tier is
instrumented by the devledger at runtime instead.

Fix patterns: hoist the fetch out of the loop, fuse the rounds into
one dispatch (``propose_rounds``-style trains), or — when the
per-round fetch is genuinely required — route it through
``obs.devledger.ledger.fetch`` so the tax is at least accounted, and
baseline the finding with that justification.
"""

from __future__ import annotations

import ast
import os

from .engine import Checker, Finding, dotted_name, iter_functions

_NP_FETCH = {"asarray", "array"}
_NP_NAMES = {"np", "numpy"}


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit``, ``jax.jit(...)``, or
    ``functools.partial(jax.jit, ...)`` expressions."""
    if isinstance(node, ast.Call):
        leaf = dotted_name(node.func).split(".")[-1]
        if leaf == "jit":
            return True
        if leaf == "partial":
            return any(
                dotted_name(a).split(".")[-1] == "jit"
                for a in node.args)
        return False
    return dotted_name(node).split(".")[-1] == "jit"


def _jit_roots_of(tree: ast.AST) -> set[str]:
    """Names bound to jitted callables in one module."""
    roots: set[str] = set()
    for _scope, fn in iter_functions(tree):
        if any(_is_jit_expr(dec) for dec in fn.decorator_list):
            roots.add(fn.name)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _is_jit_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    roots.add(t.id)
    return roots


class DeviceBoundaryChecker(Checker):
    name = "device-boundary"
    targets = ("etcd_tpu/", "scripts/", "bench.py")

    def __init__(self):
        self._module_roots: dict[str, set[str]] = {}

    # -- cross-module jit-root resolution ---------------------------------

    def _roots_of_path(self, path: str) -> set[str]:
        cached = self._module_roots.get(path)
        if cached is not None:
            return cached
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read(), filename=path)
            roots = _jit_roots_of(tree)
        except (OSError, SyntaxError):
            roots = set()
        self._module_roots[path] = roots
        return roots

    def _imported_jit_roots(self, tree: ast.AST, relpath: str,
                            root: str | None) -> set[str]:
        if root is None:
            return set()
        pkg = relpath.split("/")[:-1]  # package dirs of this module
        out: set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.level:
                base = pkg[:len(pkg) - (node.level - 1)]
                if node.level - 1 > len(pkg):
                    continue
            else:
                base = []
            parts = base + (node.module.split(".")
                            if node.module else [])
            for cand in (os.path.join(root, *parts) + ".py",
                         os.path.join(root, *parts, "__init__.py")):
                if os.path.exists(cand):
                    mod_roots = self._roots_of_path(cand)
                    for alias in node.names:
                        if alias.name in mod_roots:
                            out.add(alias.asname or alias.name)
                    break
        return out

    # -- the check --------------------------------------------------------

    def check(self, relpath, tree, source, root=None, ctx=None):
        jit_roots = _jit_roots_of(tree) \
            | self._imported_jit_roots(tree, relpath, root)
        if not jit_roots:
            return []
        findings: list[Finding] = []
        seen: set[int] = set()
        for scope, fn in iter_functions(tree):
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                self._check_loop(relpath, scope, loop, jit_roots,
                                 findings, seen)
        return findings

    def _check_loop(self, relpath, scope, loop, jit_roots,
                    findings, seen) -> None:
        def is_root_call(node) -> bool:
            return (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jit_roots)

        assigned: set[str] = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign) \
                    and is_root_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
        for node in ast.walk(loop):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _NP_FETCH
                    and dotted_name(node.func.value) in _NP_NAMES
                    and node.args):
                continue
            arg = node.args[0]
            detail = None
            if is_root_call(arg):
                detail = arg.func.id
            elif isinstance(arg, ast.Name) and arg.id in assigned:
                detail = arg.id
            if detail is None or id(node) in seen:
                continue
            seen.add(id(node))
            findings.append(Finding(
                checker=self.name, path=relpath, line=node.lineno,
                rule="per-round-fetch", scope=scope,
                message=f"np.{node.func.attr}({detail}...) inside a "
                        f"per-round loop materializes a jitted "
                        f"result every iteration — batch the rounds "
                        f"or hoist the fetch (devledger.fetch if the "
                        f"per-round fetch is load-bearing)",
                detail=detail))
