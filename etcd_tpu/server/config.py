"""Server configuration (reference etcdserver/config.go,
cluster_state.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .cluster import Cluster

CLUSTER_STATE_NEW = "new"
CLUSTER_STATE_VALUES = (CLUSTER_STATE_NEW,)


@dataclass
class ServerConfig:
    name: str = "default"
    discovery_url: str = ""
    client_urls: list[str] = field(default_factory=list)
    data_dir: str = ""
    snap_count: int = 0
    cluster: Cluster = field(default_factory=Cluster)
    cluster_state: str = CLUSTER_STATE_NEW
    # WAL-replay execution backend: "host" = sequential Python path,
    # "tpu" = batched device replay (wal/replay_device.py), "auto" =
    # device for large logs, host for small ones (compile latency).
    storage_backend: str = "auto"
    # peer transport TLS (utils.transport.TLSInfo); None/empty = http
    peer_tls: object = None

    def verify(self) -> None:
        """Reference config.go:24-43."""
        m = self.cluster.find_name(self.name)
        if m is None:
            raise ValueError(
                f"could not find name {self.name!r} in cluster")
        url_map = set()
        for memb in self.cluster.values():
            for url in memb.peer_urls:
                if url in url_map:
                    raise ValueError(
                        f"duplicate url {url!r} in server config")
                url_map.add(url)
