"""Co-hosted multi-group server: G raft groups behind the serving seams.

The reference binds ONE raft group to one process
(etcdserver/server.go:191-218); its in-process cluster tests wire N
real servers through an injected send function
(server_test.go:370-447).  This module is that pattern generalized the
TPU-first way: ALL M members of G co-hosted groups live in one
process, consensus for every group advances in ONE fused device round
per batch (raft/multiraft.py), and the serving seams are the same ones
the reference exposes —

- **Request path**: ``do(Request)`` routes a client write to its
  group (first path segment → group, sha1-hashed like member IDs,
  cluster.py) and blocks on the wait registry until the entry commits
  and applies (server.go:337-380's propose→wait pattern).
- **Storage seam**: one WAL stream per server (wal/wal.py — same
  record framing, device-replayable as a single batch) multiplexing
  all groups via :class:`~etcd_tpu.wire.GroupEntry` envelopes, plus
  commit-frontier markers; snapshots via the standard Snapshotter.
  Entries are durable BEFORE client acks (the Ready contract,
  node.go:41-60, translated to the co-hosted fate-sharing model).
- **Store seam**: one shared KV tree; group namespaces are path
  prefixes, so watches/TTLs/stats work unchanged.

Durability model (differs from per-member WALs, deliberately): the M
co-hosted members share process fate, so the durability unit is the
*server*, not the member — one WAL records appended entries and the
per-group commit frontier; restart replays committed prefixes and
re-elects.  Entries beyond the last persisted frontier were never
client-acked and are dropped on restart (timeout semantics permit
either outcome).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..obs import metrics as _obs
from ..snap import NoSnapshotError, Snapshotter
from ..store import Store
from ..utils.backoff import Backoff
from ..utils.errors import EtcdNoSpace
from ..utils.trace import maybe_start_jax_profile, tracer
from ..utils.wait import Wait
from ..wal import WAL, exist as wal_exist
from ..wire import Entry, GroupEntry, HardState, Snapshot
from ..wire.requests import Info, Request
from .cluster import ClusterStore
from .server import (
    DEFAULT_SNAP_COUNT,
    Response,
    ServerStoppedError,
    _replay_wal,
    apply_request_to_store,
    gen_id,
)
from .stats import LeaderStats, ServerStats

log = logging.getLogger(__name__)

TICK_INTERVAL = 0.1        # reference server.go:182

# obs seams (PR 2): apply-loop shape + election churn, process-wide
_M_APPLY_S = _obs.registry.histogram("etcd_apply_seconds")
_M_APPLY_N = _obs.registry.histogram("etcd_apply_batch_entries")
_M_CAMPAIGNS = _obs.registry.counter("etcd_election_campaigns_total")
_M_WINS = _obs.registry.counter("etcd_election_wins_total")
# read serve paths (PR 7): the co-hosted tier is single-copy — every
# member shares ONE store and writes ack only after apply, so a local
# read is linearizable by construction ("cohosted"); the serializable
# label marks the explicit opt-out for parity with the dist tier
_M_READ_COHOSTED = _obs.registry.counter(
    "etcd_read_serve_total", path="cohosted", outcome="ok")
_M_READ_SERIALIZABLE = _obs.registry.counter(
    "etcd_read_serve_total", path="serializable", outcome="ok")


def group_of(path: str, g: int) -> int:
    """Deterministic namespace → group routing: sha1 of the first
    path segment (the same hash family as member IDs, member.go:37)."""
    ns = path.strip("/").split("/", 1)[0]
    h = hashlib.sha1(ns.encode()).digest()
    return int.from_bytes(h[:8], "big") % g


@dataclass
class _Pending:
    req: Request
    data: bytes
    id: int
    retries: int = 0
    # explicit group routing (ConfChange entries target a group
    # directly instead of hashing a client path)
    group: int | None = None


class MultiGroupServer:
    """G co-hosted raft groups serving one namespaced KV tree."""

    def __init__(self, data_dir: str, *, g: int = 64, m: int = 3,
                 cap: int = 1024, name: str = "multigroup",
                 snap_count: int = DEFAULT_SNAP_COUNT,
                 storage_backend: str = "auto",
                 max_batch_ents: int = 32,
                 tick_interval: float = TICK_INTERVAL,
                 sync_interval: float = 0.5,
                 spare_member_slots: int = 1,
                 client_urls: list[str] | None = None,
                 mesh=None):
        from ..raft.multiraft import MultiRaft

        if mesh is not None:
            # validate BEFORE any disk mutation (a post-WAL failure
            # would make the corrected retry look like a restart)
            from ..parallel.mesh import check_group_divisible

            check_group_divisible(mesh, g)

        # ``m`` live members now; ``spare_member_slots`` empty slots
        # are allocated so runtime AddMember has somewhere to land
        # (batched state is static-shaped — slots are pre-sized, the
        # members mask is what a committed ConfChange flips)
        self.g, self.m = g, m + spare_member_slots
        self.live = m
        self.name = name
        self.snap_count = snap_count or DEFAULT_SNAP_COUNT
        self.backend = storage_backend
        self.tick_interval = tick_interval
        self.sync_interval = sync_interval
        self._campaign_slot = 0
        self.id = int.from_bytes(
            hashlib.sha1(name.encode()).digest()[:8], "big") & (2**63 - 1)

        self.store = Store()
        # decoupled watch delivery (PR 9): the fused apply loop only
        # queues events; match + watcher puts run on the engine thread
        self.store.fanout.start()
        self.w = Wait()
        self.done = threading.Event()
        self._thread: threading.Thread | None = None
        self._queue: queue.Queue[_Pending | None] = queue.Queue()
        self._requeue: list[deque[_Pending]] = [deque() for _ in range(g)]

        self.server_stats = ServerStats(name, self.id)
        self.leader_stats = LeaderStats(self.id)
        self.cluster_store = ClusterStore(self.store)
        self._client_urls = client_urls or []

        os.makedirs(data_dir, mode=0o700, exist_ok=True)
        self._snapdir = os.path.join(data_dir, "snap")
        os.makedirs(self._snapdir, mode=0o700, exist_ok=True)
        self._waldir = os.path.join(data_dir, "wal")
        crc_fn = None
        if storage_backend != "host":
            try:
                from ..ops.crc_kernel import auto_crc32c

                crc_fn = auto_crc32c
            except ImportError:
                pass
        from ..snap import DEFAULT_SNAP_KEEP

        self.ss = Snapshotter(
            self._snapdir, crc_fn=crc_fn,
            keep=int(os.environ.get("ETCD_SNAP_KEEP",
                                    DEFAULT_SNAP_KEEP)))

        self.seq = 0                      # global WAL entry sequence
        self.applied = np.zeros(g, np.int64)   # per-group applied idx
        self.raft_index = 0               # applied entries total
        self.raft_term = 0
        self._snapi = 0                   # raft_index at last snapshot
        # NOSPACE read-only mode (PR 10): a persist that hits
        # EtcdNoSpace HOLDS its (assigned, ents, hardstate) batch —
        # applies and client acks wait behind the held persist,
        # which retries at probe cadence; meanwhile writes are
        # rejected with errorCode 405 and reads keep serving off the
        # shared store.
        self._nospace = False
        self._held: tuple | None = None
        self._nospace_backoff = Backoff(base=0.25, cap=5.0,
                                        site="nospace_probe")
        self._nospace_probe_t = 0.0
        self._m_nospace = _obs.registry.gauge("etcd_nospace_active")

        if wal_exist(self._waldir):
            self._restart(cap, max_batch_ents)
        else:
            self.mr = MultiRaft(g, self.m, cap,
                                max_batch_ents=max_batch_ents,
                                live=self.live)
            self.wal = WAL.create(self._waldir,
                                  Info(id=self.id).marshal())
            # seq-0 zero-frontier marker: WAL replay requires entry
            # indices contiguous from the open index (wal.go:171-175)
            zero = np.zeros(g, np.int32).tobytes()
            self.wal.save(HardState(), [Entry(
                index=0, term=0,
                data=GroupEntry(kind=1, payload=zero + zero)
                .marshal())])
        # intra-slice scale-out: the co-hosted batch sharded over a
        # local device mesh (after restart seeding so the replayed
        # arrays get placed too)
        self.mesh = mesh
        if mesh is not None:
            self.mr.shard(mesh)

    # -- bootstrap / restart ---------------------------------------------

    def _restart(self, cap: int, max_batch_ents: int) -> None:
        """Snapshot + WAL replay → store + re-seeded consensus state.

        The WAL is replayed through the backend-honoring seam
        (server.py:_replay_wal — device batch replay when it pays);
        only entries at or below the last persisted commit frontier
        apply (never-acked tails drop); every member re-seeds with the
        committed log's compacted form and fresh elections start above
        the replayed term.
        """
        from ..raft.multiraft import MultiRaft

        g = self.g
        frontier = np.zeros(g, np.int64)
        terms = np.zeros(g, np.int64)
        snap_index = 0
        try:
            snap = self.ss.load()
        except NoSnapshotError:
            snap = None
        applied_total = 0
        if snap is not None:
            blob = json.loads(snap.data.decode())
            if len(blob["frontier"]) != g:
                raise RuntimeError(
                    f"snapshot was written with --cohosted-groups "
                    f"{len(blob['frontier'])}, not {g}")
            self.store.recovery(blob["store"].encode())
            frontier = np.asarray(blob["frontier"], np.int64)
            terms = np.asarray(blob["terms"], np.int64)
            snap_index = blob["seq"]
            applied_total = blob.get("applied_total", 0)
            log.info("multigroup: restart from snapshot seq=%d",
                     snap_index)
        snap_frontier = frontier.copy()
        # an empty post-snapshot tail must not reset the sequence
        self.seq = snap_index

        from .gereplay import scan as ge_stream_scan
        from .server import _replay_wal_raw

        # restart replay routes through the measured backend policy
        # (stage "restart" — the r05 24x tunnel-bound regression is
        # the case the router exists to prevent)
        self.wal, md, hard_state, raw = _replay_wal_raw(
            self._waldir, snap_index, self.backend, stage="restart")
        info = Info.unmarshal(md or b"")
        if info.id != self.id:
            raise RuntimeError(
                f"unexpected server id {info.id:x}, want {self.id:x}")

        # array pass: ONE native envelope sweep + vectorized
        # last-record-wins dedup and frontier selection — the device
        # replay hands back struct-of-arrays and the restart stays in
        # that shape instead of walking 1M GroupEntry objects
        # (round-2 weakness #5)
        stream = ge_stream_scan(raw)
        if len(stream):
            self.seq = max(self.seq, int(stream.seq.max()))
        fpos = stream.last_of_kind(1)
        if fpos >= 0:
            v = np.frombuffer(stream.payload(fpos), np.int32)
            if v.size != 2 * g:
                raise RuntimeError(
                    f"data dir was written with --cohosted-groups "
                    f"{v.size // 2}, not {g}; group routing would "
                    f"silently change")
            frontier = v[:g].astype(np.int64)
            terms = v[g:2 * g].astype(np.int64)

        # committed winners apply in stream order; only the applying
        # slice materializes Python objects (CONFCHANGE entries touch
        # the engine, not the store — they re-apply after seeding)
        winners = stream.winner_positions()
        committed = winners[
            (stream.gindex[winners] > snap_frontier[
                stream.group[winners]])
            & (stream.gindex[winners] <= frontier[
                stream.group[winners]])]
        conf_changes: list[tuple[int, Request]] = []
        applied_n = int(committed.size)
        for k in committed:
            payload = stream.payload(int(k))
            if not payload:
                continue
            r = Request.unmarshal(payload)
            if r.method == "CONFCHANGE":
                conf_changes.append((int(stream.group[k]), r))
            else:
                apply_request_to_store(self.store, r)

        self.applied = frontier.copy()
        self.raft_index = applied_total + applied_n
        self.raft_term = int(terms.max()) if g else 0
        self._snapi = self.raft_index

        # re-seed consensus: every member holds the committed log in
        # compacted form (offset = last = commit = applied = frontier,
        # slot 0 carries the frontier term for match checks)
        import jax.numpy as jnp

        mr = MultiRaft(g, self.m, cap, max_batch_ents=max_batch_ents,
                       live=self.live)
        fr = jnp.asarray(frontier, jnp.int32)
        tm = jnp.asarray(terms, jnp.int32)
        slot0 = jnp.zeros((g, cap), jnp.int32).at[:, 0].set(tm)
        members = None
        if snap is not None and "members" in blob:
            msnap = np.asarray(blob["members"], bool)
            if msnap.shape[1] < self.m:
                # restart with MORE spare slots: pad the mask (new
                # slots start empty — the add_member migration path)
                msnap = np.pad(msnap,
                               ((0, 0), (0, self.m - msnap.shape[1])))
            elif msnap.shape[1] > self.m:
                extra = msnap[:, self.m:]
                if extra.any():
                    raise RuntimeError(
                        f"snapshot uses member slot(s) >= {self.m}; "
                        f"restart with spare_member_slots >= "
                        f"{msnap.shape[1] - self.live}")
                msnap = msnap[:, :self.m]
            members = jnp.asarray(msnap)
        for s in range(self.m):
            st = mr.states[s]
            st = st._replace(
                term=tm, offset=fr, last=fr, commit=fr, applied=fr,
                log_term=slot0)
            if members is not None:
                st = st._replace(
                    members=members,
                    nmembers=members.sum(axis=1).astype(jnp.int32))
            mr.states[s] = st
        self.mr = mr
        # committed ConfChanges in the replayed window re-apply to
        # the fresh engine (the snapshot's members mask carries
        # everything below it)
        for gi, r in conf_changes:
            self._apply_conf_change(gi, r)
        log.info("multigroup: replayed %d records, %d applied, "
                 "max term %d", len(stream), applied_n,
                 self.raft_term)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        maybe_start_jax_profile()
        self._register_self()
        # bootstrap election + one replication round BEFORE serving:
        # the first fused-round jit compile (seconds) must not eat
        # into early clients' 500ms request timeouts
        if (self.mr.leader < 0).any():
            with tracer.span("mg.bootstrap_election"):
                self._campaign_and_fence(self.mr.leader < 0)
        else:
            self._absorb_commits({})
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def _register_self(self) -> None:
        """Register this server under /_etcd/machines so
        /v2/machines serves real endpoints (member.go:15,57's
        replicated-registry pattern; idempotent across restarts)."""
        from .cluster import Member

        try:
            self.cluster_store.add(Member(
                id=self.id, name=self.name,
                peer_urls=self._client_urls,
                client_urls=self._client_urls))
        except Exception:
            pass  # already registered (e.g. restored from snapshot)

    def _campaign_and_fence(self, mask) -> None:
        """Elect leaders for the masked groups, then persist fence
        records for the becoming-leader empty entries: they consume a
        gindex without a client payload, and an older never-acked
        record at that index must not win the next restart's replay
        (last-record-wins would resurrect dropped data)."""
        mr = self.mr
        slot = self._campaign_slot
        self._campaign_slot = (slot + 1) % self.m
        mask_np = np.asarray(mask, bool)
        won = mr.campaign(slot, mask=mask_np)
        _M_CAMPAIGNS.inc(int(mask_np.sum()))
        _M_WINS.inc(int(won.sum()))
        fences: list[Entry] = []
        if won.any():
            base = mr.last_base
            valid = mr.last_valid
            terms_now = np.max(np.stack(
                [np.asarray(st.term) for st in mr.states]), axis=0)
            for gi in np.nonzero(won & valid)[0]:
                self.seq += 1
                fences.append(Entry(
                    index=self.seq, term=int(terms_now[gi]),
                    data=GroupEntry(
                        kind=0, group=int(gi),
                        gindex=int(base[gi]) + 1,
                        gterm=int(terms_now[gi])).marshal()))
        self._absorb_commits({}, fences)

    def stop(self) -> None:
        self.done.set()
        self._queue.put(None)  # wake the loop
        if self._thread is not None \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
        self.store.fanout.close()
        self.wal.close()

    # -- client request path ----------------------------------------------

    def do(self, r: Request, timeout: float | None = None) -> Response:
        """The serving seam (server.go:337-380): writes and quorum
        reads go through their group's consensus; plain GETs and
        watches serve from the shared store."""
        if r.id == 0:
            raise ValueError("r.id cannot be 0")
        if r.method == "GET" and r.quorum:
            r.method = "QGET"
        if r.method in ("POST", "PUT", "DELETE", "QGET"):
            if self._nospace:
                # read-only NOSPACE mode: the distinct error code
                # (reads below keep serving the shared store)
                raise EtcdNoSpace(
                    cause="member is read-only (NOSPACE)")
            ch = self.w.register(r.id)
            self._queue.put(_Pending(req=r, data=r.marshal(), id=r.id))
            try:
                x = ch.get(timeout=timeout)
            except queue.Empty:
                self.w.trigger(r.id, None)  # GC wait
                raise TimeoutError("request timed out")
            if x is None:
                if self.done.is_set():
                    raise ServerStoppedError()
                raise TimeoutError("request dropped (no leader)")
            if x.err is not None:
                raise x.err
            return x
        if r.method == "GET":
            if r.wait:
                wc = self.store.watch(r.path, r.recursive, r.stream,
                                      r.since)
                return Response(watcher=wc)
            if r.serializable:
                _M_READ_SERIALIZABLE.inc()
                self.store.stats.inc_read_path("serializable")
            else:
                _M_READ_COHOSTED.inc()
                self.store.stats.inc_read_path("cohosted")
            ev = self.store.get(r.path, r.recursive, r.sorted)
            return Response(event=ev)
        from .server import UnknownMethodError

        raise UnknownMethodError(r.method)

    # -- runtime membership (server.go:382-404, 542-559 batched) ----------

    def add_member(self, slot: int,
                   timeout: float | None = 30.0) -> None:
        """Grow every group's cluster to include member ``slot``: one
        ConfChange entry per group, proposed through THAT group's log
        and applied only once committed (quorum under the OLD
        membership authorizes the change, as in the reference's
        ProposeConfChange → applyConfChange path)."""
        self._conf_change(True, slot, timeout)

    def remove_member(self, slot: int,
                      timeout: float | None = 30.0) -> None:
        """Shrink every group's cluster: the removed slot's progress
        stops counting toward quorums the moment the entry commits;
        a removed leader's groups elect fresh on the next timeout."""
        self._conf_change(False, slot, timeout)

    def _conf_change(self, add: bool, slot: int,
                     timeout: float | None) -> None:
        if not (0 <= slot < self.m):
            raise ValueError(
                f"slot {slot} out of range (allocated {self.m} "
                f"member slots; grow spare_member_slots to add more)")
        payload = json.dumps({"add": bool(add), "slot": int(slot)})
        chans = []
        for gi in range(self.g):
            r = Request(method="CONFCHANGE", id=gen_id(),
                        path=f"/_confchange/{gi}", val=payload)
            ch = self.w.register(r.id)
            chans.append((r.id, ch))
            self._queue.put(_Pending(req=r, data=r.marshal(),
                                     id=r.id, group=gi))
        deadline = None if timeout is None else time.time() + timeout
        for rid, ch in chans:
            left = None if deadline is None \
                else max(deadline - time.time(), 0.01)
            try:
                x = ch.get(timeout=left)
            except queue.Empty:
                self.w.trigger(rid, None)
                raise TimeoutError(
                    "conf change timed out (some groups uncommitted)")
            if x is None:
                raise ServerStoppedError() if self.done.is_set() \
                    else TimeoutError("conf change dropped")

    def _apply_conf_change(self, gi: int, r: Request) -> None:
        d = json.loads(r.val)
        mask = np.zeros(self.g, bool)
        mask[gi] = True
        self.mr.apply_conf_change(bool(d["add"]), int(d["slot"]),
                                  mask=mask)

    def members_of(self, gi: int) -> np.ndarray:
        """[M] live-membership mask of group ``gi`` (slot capacity M;
        quorum = live//2 + 1)."""
        return np.asarray(self.mr.states[0].members)[gi]

    # -- RaftTimer --------------------------------------------------------

    def index(self) -> int:
        return self.raft_index

    def term(self) -> int:
        return self.raft_term

    # -- the batched apply loop -------------------------------------------

    def run(self) -> None:
        """The co-hosted generalization of the reference run() loop
        (server.go:247-323): drain a batch of proposals, ONE fused
        consensus round for all groups, persist, apply, ack."""
        mr = self.mr
        next_tick = time.monotonic() + self.tick_interval
        next_sync = time.monotonic() + self.sync_interval
        batch: list[_Pending] = []

        while not self.done.is_set():
            batch = self._drain(timeout=min(
                self.tick_interval,
                max(next_tick - time.monotonic(), 0.001)))
            if self.done.is_set():
                break
            now = time.monotonic()
            if self._nospace:
                # read-only: reject queued writes with the typed
                # code, retry the held persist at probe cadence,
                # and propose nothing new (the engine log must not
                # outgrow a WAL that cannot take records)
                err = EtcdNoSpace(
                    cause="member is read-only (NOSPACE)")
                for p in batch:
                    self.w.trigger(p.id, Response(err=err))
                for q in self._requeue:
                    while q:
                        self.w.trigger(q.popleft().id,
                                       Response(err=err))
                if now >= self._nospace_probe_t:
                    self._nospace_recover()
                continue
            if now >= next_tick:
                if (mr.leader < 0).any():
                    self._campaign_and_fence(mr.leader < 0)
                next_tick = now + self.tick_interval
            if now >= next_sync:
                # TTL expiry: co-hosted members share ONE store, so
                # the reference's proposal-carried SYNC determinism
                # (server.go:438-456) is vacuous here — expire
                # directly on the shared tree
                self.store.delete_expired_keys(time.time())
                next_sync = now + self.sync_interval

            n_new = np.zeros(self.g, np.int32)
            data: list[list[bytes]] = [[] for _ in range(self.g)]
            items: list[list[_Pending]] = [[] for _ in range(self.g)]
            for gi in range(self.g):
                q = self._requeue[gi]
                while q and len(items[gi]) < mr.e:
                    items[gi].append(q.popleft())
            for p in batch:
                gi = p.group if p.group is not None \
                    else group_of(p.req.path, self.g)
                if len(items[gi]) >= mr.e:
                    self._requeue[gi].append(p)
                    continue
                items[gi].append(p)
            for gi in range(self.g):
                n_new[gi] = len(items[gi])
                data[gi] = [p.data for p in items[gi]]

            if not n_new.any() and (mr.commit_index() ==
                                    self.applied).all():
                # idle heartbeat round only when a leader exists
                if (mr.leader >= 0).any():
                    mr.replicate()
                self._absorb_commits({})
                continue

            with tracer.stage("mg.consensus_round"):
                mr.propose(n_new, data=data)
            valid = mr.last_valid
            base = mr.last_base
            terms_now = np.max(np.stack(
                [np.asarray(st.term) for st in mr.states]),
                axis=0).astype(np.int32)
            assigned: dict[tuple[int, int], _Pending] = {}
            to_persist: list[Entry] = []
            for gi in range(self.g):
                if not items[gi]:
                    continue
                if not valid[gi]:
                    # no leader / overflow: retry a few rounds, then
                    # fail the clients (reference: request timeout)
                    for p in items[gi]:
                        p.retries += 1
                        if p.retries < 50:
                            self._requeue[gi].append(p)
                        else:
                            self.w.trigger(p.id, None)
                    continue
                for j, p in enumerate(items[gi]):
                    idx = int(base[gi]) + 1 + j
                    assigned[(gi, idx)] = p
                    self.seq += 1
                    to_persist.append(Entry(
                        index=self.seq, term=self.raft_term,
                        data=GroupEntry(
                            kind=0, group=gi, gindex=idx,
                            gterm=int(terms_now[gi]),
                            payload=p.data).marshal()))

            self._absorb_commits(assigned, to_persist, terms_now)
            if mr.errors["overflow"].any():
                # compaction AFTER absorb: mark_applied(self.applied)
                # inside _absorb_commits bounds it, so committed-but-
                # unapplied payloads are never pruned
                mr.compact()

        # server stopping: promptly release EVERY waiter — the final
        # drained batch, anything still queued, and the requeues
        for p in batch:
            self.w.trigger(p.id, None)
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            if p is not None:
                self.w.trigger(p.id, None)
        for q in self._requeue:
            while q:
                self.w.trigger(q.popleft().id, None)

    def _drain(self, timeout: float) -> list[_Pending]:
        """Block briefly for the first proposal, then sweep the rest
        (request pipelining: one device round serves the batch)."""
        out: list[_Pending] = []
        try:
            p = self._queue.get(timeout=timeout)
        except queue.Empty:
            return out
        if p is not None:
            out.append(p)
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                return out
            if p is not None:
                out.append(p)

    def _absorb_commits(self, assigned, to_persist=None,
                        terms_now=None) -> None:
        """Persist-then-apply: newly appended entries and the commit
        frontier go to the WAL (fsync) BEFORE any client ack — the
        Ready contract's ordering (node.go:41-60) at batch level."""
        mr = self.mr
        if self._nospace:
            # applies and acks queue behind the held persist; the
            # recovery path re-runs this once the save lands
            return
        commit = mr.commit_index().astype(np.int64)
        newly = commit > self.applied
        if to_persist or newly.any():
            terms = np.zeros(self.g, np.int32)
            if newly.any():
                if terms_now is None:
                    terms_now = np.max(np.stack(
                        [np.asarray(st.term) for st in mr.states]),
                        axis=0).astype(np.int32)
                terms = terms_now
                self.raft_term = max(self.raft_term,
                                     int(terms.max()))
            frontier = GroupEntry(
                kind=1, payload=commit.astype(np.int32).tobytes()
                + terms.tobytes()).marshal()
            self.seq += 1
            ents = (to_persist or []) + [
                Entry(index=self.seq, term=self.raft_term,
                      data=frontier)]
            hs = HardState(term=self.raft_term, vote=0,
                           commit=self.seq)
            try:
                with tracer.stage("mg.persist"):
                    self.wal.save(hs, ents)
            except EtcdNoSpace as e:
                # full disk: HOLD the batch (seqs stay allocated —
                # the WAL rolled its file back, so re-writing the
                # same records at recovery is seq-contiguous) and go
                # read-only.  Nothing applies and nothing acks until
                # the save lands: the Ready-contract ordering is
                # preserved by simply not advancing.
                self._held = (dict(assigned), ents, hs)
                self._enter_nospace(e)
                return

        if not newly.any():
            return
        n_apply = int((commit - self.applied)[newly].sum())
        t0 = time.perf_counter()
        with tracer.stage("mg.apply"):
            self._apply_newly(assigned, commit, newly)
        _M_APPLY_N.observe(n_apply)
        _M_APPLY_S.observe(time.perf_counter() - t0)
        mr.mark_applied(self.applied)

        if self.raft_index - self._snapi > self.snap_count:
            try:
                self.snapshot()
            except EtcdNoSpace as e:
                # snapshot save / cut hit a full disk: degrade to
                # read-only (the trigger re-fires after recovery)
                self._enter_nospace(e)

    # -- NOSPACE read-only mode (PR 10) -----------------------------------

    def _enter_nospace(self, e: EtcdNoSpace) -> None:
        if not self._nospace:
            self._nospace = True
            self._nospace_backoff.reset()
            self._m_nospace.set(1)
            log.error("multigroup: ENTERING NOSPACE read-only mode "
                      "(%s): writes rejected with errorCode 405, "
                      "reads keep serving", e.cause)
        self._nospace_probe_t = (time.monotonic()
                                 + self._nospace_backoff.next())

    def _exit_nospace(self) -> None:
        if self._nospace:
            self._nospace = False
            self._nospace_backoff.reset()
            self._m_nospace.set(0)
            log.warning("multigroup: NOSPACE recovered — accepting "
                        "writes again")

    def _nospace_recover(self) -> None:
        """Run-loop probe: re-persist the held batch (same seqs —
        the WAL rolled its file back to the pre-batch mark), then
        apply + ack it; without a held batch just probe the disk."""
        try:
            held = self._held
            if held is not None:
                assigned, ents, hs = held
                with tracer.stage("mg.persist"):
                    self.wal.save(hs, ents)
                self._held = None
                self._exit_nospace()
                # applies + client acks ride the normal absorb path
                # now that the records are durable
                self._absorb_commits(assigned)
            else:
                self.wal.probe_space()
                self._exit_nospace()
        except EtcdNoSpace:
            self._nospace_probe_t = (time.monotonic()
                                     + self._nospace_backoff.next())

    def _apply_newly(self, assigned, commit, newly) -> None:
        mr = self.mr
        with self.store.fanout_round():
            self._apply_newly_inner(assigned, commit, newly, mr)

    def _apply_newly_inner(self, assigned, commit, newly, mr) -> None:
        for gi in np.nonzero(newly)[0]:
            for idx in range(int(self.applied[gi]) + 1,
                             int(commit[gi]) + 1):
                payload = mr.committed_payload(int(gi), idx)
                resp = None
                if payload:
                    r = Request.unmarshal(payload)
                    if r.method == "CONFCHANGE":
                        # committed membership change: flip the
                        # engine's members mask for THIS group
                        # (reference applyConfChange,
                        # server.go:542-559)
                        self._apply_conf_change(int(gi), r)
                        resp = Response()
                    else:
                        resp = apply_request_to_store(self.store, r)
                self.raft_index += 1
                p = assigned.pop((int(gi), idx), None)
                if p is not None:
                    self.w.trigger(p.id, resp)
                else:
                    # an entry assigned in an earlier round: find its
                    # waiter via the id embedded in the request
                    if payload:
                        self.w.trigger(r.id, resp)
            self.applied[gi] = commit[gi]

    # -- snapshot / compaction --------------------------------------------

    def snapshot(self) -> None:
        """Store snapshot + frontier → snap file; compact the device
        logs; cut the WAL (server.go:562-571 batched)."""
        mr = self.mr
        terms = np.max(np.stack(
            [np.asarray(st.term) for st in mr.states]), axis=0)
        blob = json.dumps({
            "store": self.store.save().decode(),
            "frontier": [int(x) for x in self.applied],
            "terms": [int(x) for x in terms],
            "seq": self.seq,
            "applied_total": self.raft_index,
            # per-group live-membership mask: conf changes below the
            # snapshot don't need their entries replayed
            "members": np.asarray(self.mr.states[0].members)
            .astype(int).tolist(),
        }).encode()
        with tracer.span("mg.snapshot"):
            snap_seq = self.seq
            self.ss.save_snap(Snapshot(data=blob, index=snap_seq,
                                       term=self.raft_term))
            mr.compact()
            self.wal.cut()
            # snapshot is durable (save_snap fsyncs file+dir): WAL
            # segments wholly behind the OLDEST retained snapshot
            # can go — bounded disk under sustained traffic while
            # load()'s corrupt-newest fallback keeps a replayable
            # chain (PR 6; crash-ordering per WAL.gc)
            floor = self.ss.retained_floor()
            self.wal.gc(snap_seq if floor is None else floor)
        self._snapi = self.raft_index
        log.info("multigroup: snapshot at seq=%d (applied=%d)",
                 self.seq, self.raft_index)
