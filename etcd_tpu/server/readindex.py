"""Linearizable-read bookkeeping for the serving tiers (PR 7
tentpole): leader-lease clocks, batched ReadIndex queues, and
follower commit-index wait-points.

GETs were served straight off the local store replica, so a follower
(or a deposed leader) could return data the quorum had since
overwritten — the only "safe" read was a full replicated write
(QGET).  The canonical fix ported from the Paxos/Raft optimization
literature (PAPERS.md, "On the parallels between Paxos and Raft")
keeps reads OFF the WAL entirely:

- **Leader lease**: every matched append/heartbeat ack already
  proves a follower reset its election timer when the frame was
  SENT (``FrameMeta.t0``).  :class:`LeaseClock` keeps the newest
  such send time per (peer, lane); the q-th largest over a group's
  members (``ops.quorum.quorum_basis`` — the commit-quorum order
  statistic applied to time) is the latest instant a quorum
  endorsed this host's leadership.  No member of that quorum can
  vote for a new leader before ``basis + election_s``, and any new
  leader needs a vote from at least one of them, so reads served
  before ``basis + lease_s`` (``lease_s < election_s − drift``)
  cannot miss a newer leader's committed write.  Zero messages,
  zero fsyncs per read.
- **Batched ReadIndex**: when the lease cannot vouch (just elected,
  quiet cluster, lease disabled), reads register in per-group FIFO
  queues (:class:`ReadQueue`).  Confirmation piggybacks on the acks
  already flowing through the PR-5 pipeline: once ``basis`` moves
  past a read's registration time, a quorum round demonstrably
  completed AFTER the read arrived.  One vectorized ``[G]`` sweep
  releases every confirmable read at once — thousands of pending
  reads cost one basis computation, not one quorum round each.
- **Follower wait-points**: a follower fetches a confirmed read
  index from the leader and parks on :class:`WaitPoints` until its
  own apply frontier reaches it, then serves from its local replica
  (the wait-registry pattern, applied to commit indexes).

All three classes are pure bookkeeping — no I/O, no locks; every
method is called under the owning server's lock (the distpipe
discipline).  The owning server supplies the safety inputs:
``read_ok``/``floor`` (the lane's commit covers an entry of the
current term — leader-completeness gating, raft thesis §6.4) and
``lead`` (the host-cached leadership view).
"""

from __future__ import annotations

from collections import deque
from heapq import heapify, heappop, heappush

import numpy as np

from ..obs import metrics as _obs
from ..ops.quorum import quorum_basis

#: serve-path labels (the store-stats split + etcd_read_serve_total)
PATH_LEASE = "lease"
PATH_READ_INDEX = "read_index"
PATH_FOLLOWER = "follower_wait"
PATH_SERIALIZABLE = "serializable"
PATH_QUORUM = "quorum"
PATH_COHOSTED = "cohosted"


def serve_counter(path: str, outcome: str):
    """The labeled serve counter (callers cache the handles on their
    hot paths, like every other labeled-registry lookup)."""
    return _obs.registry.counter("etcd_read_serve_total",
                                 path=path, outcome=outcome)


class LeaseClock:
    """Per-(peer, lane) newest positively-acked frame SEND time.

    ``note_ack`` records the send time of a matched ack for the
    lanes the follower acknowledged at the leader's term
    (``resp.active`` — lanes where the follower adopted/held our
    term and reset its election timer).  Lanes where the follower
    answered from a higher term are excluded by that mask, so a
    deposing ack can never extend a lease.  Times only move forward
    (a late ack for an old frame cannot regress the evidence).
    """

    __slots__ = ("g", "m", "slot", "ack_t0")

    def __init__(self, g: int, m: int, slot: int):
        self.g, self.m, self.slot = g, m, slot
        self.ack_t0 = np.zeros((m, g), np.float64)

    def note_ack(self, peer: int, t0: float,
                 lanes: np.ndarray) -> None:
        row = self.ack_t0[peer]
        np.copyto(row, t0, where=np.asarray(lanes, bool)
                  & (row < t0))

    def basis(self, members: np.ndarray, nmembers: np.ndarray,
              now: float) -> np.ndarray:
        """[G] quorum confirmation basis (ops.quorum.quorum_basis)."""
        return quorum_basis(self.ack_t0, members, nmembers,
                            self.slot, now)

    def basis_one(self, gi: int, members: np.ndarray,
                  nmembers: np.ndarray, now: float) -> float:
        """Scalar fast path for one group (the per-read lease
        check): same order statistic over the group's member row."""
        v = np.where(members[gi], self.ack_t0[:, gi], -np.inf)
        if members[gi, self.slot]:
            v[self.slot] = now
        q = int(nmembers[gi]) // 2 + 1
        return float(np.sort(v)[-q])


class PendingRead:
    """One registered linearizable read (or ReadIndex RPC).

    ``n`` counts the reads riding this registration: a read_many
    batch registers ONE channel per group and folds the group's
    remaining reads into it (PR 14 — the per-read Chan allocation
    was a stage-table line), so release sweeps weight their batch
    metric by ``n``, not the queue length."""

    __slots__ = ("t0", "required", "ch", "kind", "n")

    def __init__(self, t0: float, required: int, ch, kind: str):
        self.t0 = t0            # registration time (monotonic)
        self.required = required  # leader applied at registration
        self.ch = ch            # utils.wait.Chan
        self.kind = kind        # "read" | "rd" (follower RPC)
        self.n = 1              # reads sharing this registration


class ReadQueue:
    """Per-group FIFO queues of pending linearizable reads.

    Registration order is monotone in ``t0`` within a group, so the
    release sweep only ever inspects queue heads: a vectorized
    ``[G]`` precheck masks the groups worth visiting, then heads pop
    while the confirmation condition holds — the whole sweep is one
    basis compare amortized over every pending read.
    """

    def __init__(self, g: int):
        self.g = g
        self._q: list[deque[PendingRead]] = [deque()
                                             for _ in range(g)]
        self._count = np.zeros(g, np.int64)
        self.pending = 0

    def register(self, gi: int, t0: float, required: int, ch,
                 kind: str = "read") -> PendingRead:
        pr = PendingRead(t0, required, ch, kind)
        self._q[gi].append(pr)
        self._count[gi] += 1
        self.pending += 1
        return pr

    def release(self, *, lead: np.ndarray, read_ok: np.ndarray,
                applied: np.ndarray, floor: np.ndarray,
                basis: np.ndarray, lease_until: np.ndarray,
                now: float) -> list[tuple[PendingRead, str, int]]:
        """Pop every confirmable read.  A read confirms when its
        lane is led with a current-term commit applied
        (``lead & read_ok & applied >= floor``) AND either a quorum
        round completed after it registered (``basis > t0`` — the
        batched ReadIndex) or the lane's lease vouches
        (``now < lease_until``).  Returns ``(read, path, rd)``
        tuples; ``rd`` is the index a follower must reach before
        serving (max of the leader's applied-at-registration and the
        current-term floor)."""
        if not self.pending:
            return []
        mask = ((self._count > 0) & np.asarray(lead, bool)
                & np.asarray(read_ok, bool)
                & (np.asarray(applied) >= np.asarray(floor)))
        out: list[tuple[PendingRead, str, int]] = []
        for gi in np.nonzero(mask)[0]:
            gi = int(gi)
            q = self._q[gi]
            leased = now < lease_until[gi]
            while q and (leased or basis[gi] > q[0].t0):
                pr = q.popleft()
                self._count[gi] -= 1
                self.pending -= 1
                path = PATH_LEASE if leased else PATH_READ_INDEX
                rd = max(pr.required, int(floor[gi]))
                out.append((pr, path, rd))
        return out

    def expire(self, now: float,
               max_age: float) -> list[PendingRead]:
        """Drop reads pending longer than ``max_age`` (their callers
        have long since timed out; the sweep keeps abandoned waiters
        from accumulating).  FIFO t0 order means expired reads are
        always at the heads."""
        if not self.pending:
            return []
        out: list[PendingRead] = []
        for gi in np.nonzero(self._count > 0)[0]:
            q = self._q[int(gi)]
            while q and now - q[0].t0 > max_age:
                out.append(q.popleft())
                self._count[gi] -= 1
                self.pending -= 1
        return out

    def fail_lanes(self, lanes: np.ndarray) -> list[PendingRead]:
        """Fail every read pending on the masked lanes (leadership
        lost: this host can never confirm them)."""
        if not self.pending:
            return []
        out: list[PendingRead] = []
        for gi in np.nonzero(np.asarray(lanes, bool)
                             & (self._count > 0))[0]:
            gi = int(gi)
            out.extend(self._q[gi])
            self.pending -= len(self._q[gi])
            self._q[gi].clear()
            self._count[gi] = 0
        return out

    def fail_all(self) -> list[PendingRead]:
        return self.fail_lanes(np.ones(self.g, bool))


class WaitPoints:
    """Per-group commit-index wait-points (the follower half).

    A follower read waits until the local apply frontier reaches
    the leader-confirmed read index; ``release`` pops every waiter
    satisfied by the advanced frontier (heap-ordered per group, so
    the sweep never scans past the first unsatisfied index).
    """

    def __init__(self, g: int):
        self.g = g
        self._q: list[list[tuple[int, int, object, float]]] = [
            [] for _ in range(g)]
        self._count = np.zeros(g, np.int64)
        self._seq = 0  # heap tiebreak (Chans don't compare)
        self.pending = 0

    def register(self, gi: int, index: int, ch,
                 t0: float = 0.0) -> None:
        self._seq += 1
        heappush(self._q[gi], (int(index), self._seq, ch, t0))
        self._count[gi] += 1
        self.pending += 1

    def release(self, applied: np.ndarray) -> list:
        """Pop every waiter whose index the frontier has covered;
        returns their channels."""
        if not self.pending:
            return []
        out = []
        mask = (self._count > 0)
        for gi in np.nonzero(mask)[0]:
            gi = int(gi)
            q = self._q[gi]
            while q and q[0][0] <= int(applied[gi]):
                out.append(heappop(q)[2])
                self._count[gi] -= 1
                self.pending -= 1
        return out

    def expire(self, now: float, max_age: float) -> list:
        """Drop waiters parked longer than ``max_age`` (their
        callers timed out; without this sweep a stalled apply
        frontier under a reachable leader accumulates abandoned
        waiters without bound — the same leak ReadQueue.expire
        plugs on the leader side).  Heap order is by index, not
        age, so this scans and re-heapifies the touched groups —
        callers gate it on a coarse cadence."""
        if not self.pending:
            return []
        out = []
        for gi in np.nonzero(self._count > 0)[0]:
            gi = int(gi)
            q = self._q[gi]
            keep = [e for e in q if now - e[3] <= max_age]
            if len(keep) != len(q):
                out.extend(e[2] for e in q
                           if now - e[3] > max_age)
                heapify(keep)
                self._q[gi] = keep
                self._count[gi] = len(keep)
        self.pending -= len(out)
        return out

    def fail_all(self) -> list:
        out = []
        for gi in range(self.g):
            out.extend(e[2] for e in self._q[gi])
            self._q[gi].clear()
        self._count[:] = 0
        self.pending = 0
        return out


def lease_drift_ticks(election: int) -> int:
    """The clock-drift safety margin (ticks) the lease band must
    clear: ``lease < election − drift``.  One tick absorbs scheduler
    jitter on equal clocks; the 10% term scales with the election
    window for real inter-host drift (the etcd clock-drift bound).
    Shared by the runtime validation (DistServer/cli) and the
    static lease-band checker (analysis/timeouts.py) so the two can
    never disagree about the band."""
    return max(1, election // 10)


__all__ = [
    "LeaseClock", "PendingRead", "ReadQueue", "WaitPoints",
    "PATH_COHOSTED", "PATH_FOLLOWER", "PATH_LEASE", "PATH_QUORUM",
    "PATH_READ_INDEX", "PATH_SERIALIZABLE", "lease_drift_ticks",
    "serve_counter",
]
