"""Server-level statistics (/v2/stats/self and /v2/stats/leader).

The 0.5-alpha reference tracks only store op counters and never wires
an HTTP stats endpoint (SURVEY §5.5 — 0.4.x had /v2/stats, documented
in Documentation/api.md); observability is called out there as new
work for the rebuild, so this module provides the classic field shape
plus counters fed from the apply loop and peer transport.
"""

from __future__ import annotations

import json
import threading
import time

STATE_NAMES = ("StateFollower", "StateCandidate", "StateLeader")


class ServerStats:
    """Process-wide serving counters, lock-guarded (the host control
    plane is threaded; device state needs no such guard)."""

    def __init__(self, name: str, id: int):
        self.name = name
        self.id = id
        self.start_time = time.time()
        self._lock = threading.Lock()
        self.state = "StateFollower"
        self.leader_id = 0
        self.leader_since = None
        self.recv_append_cnt = 0
        self.send_append_cnt = 0

    def recv_append(self) -> None:
        with self._lock:
            self.recv_append_cnt += 1

    def send_append(self) -> None:
        with self._lock:
            self.send_append_cnt += 1

    def set_state(self, state_idx: int, leader_id: int) -> None:
        with self._lock:
            name = STATE_NAMES[state_idx] \
                if 0 <= state_idx < 3 else "StateFollower"
            if leader_id != self.leader_id or name != self.state:
                self.leader_since = time.time()
            self.state = name
            self.leader_id = leader_id

    def to_dict(self) -> dict:
        with self._lock:
            now = time.time()
            uptime = now - (self.leader_since or now)
            return {
                "name": self.name,
                "id": f"{self.id:x}",
                "state": self.state,
                "startTime": time.strftime(
                    "%Y-%m-%dT%H:%M:%S%z",
                    time.localtime(self.start_time)),
                "leaderInfo": {
                    "leader": f"{self.leader_id:x}",
                    "uptime": f"{uptime:.6f}s",
                },
                "recvAppendRequestCnt": self.recv_append_cnt,
                "sendAppendRequestCnt": self.send_append_cnt,
            }

    def to_json(self) -> bytes:
        return json.dumps(self.to_dict()).encode()


class LeaderStats:
    """Per-follower replication counters while this member leads."""

    def __init__(self, id: int):
        self.id = id
        self._lock = threading.Lock()
        self.followers: dict[str, dict] = {}

    def _entry(self, follower_id: int) -> dict:
        return self.followers.setdefault(
            f"{follower_id:x}",
            {"latency": {"current": 0.0, "average": 0.0,
                         "minimum": float("inf"), "maximum": 0.0},
             "counts": {"success": 0, "fail": 0}})

    def observe(self, follower_id: int, latency_s: float) -> None:
        with self._lock:
            f = self._entry(follower_id)
            lat = f["latency"]
            cnt = f["counts"]
            cnt["success"] += 1
            ms = latency_s * 1e3
            lat["current"] = ms
            lat["minimum"] = min(lat["minimum"], ms)
            lat["maximum"] = max(lat["maximum"], ms)
            lat["average"] += (ms - lat["average"]) / cnt["success"]

    def fail(self, follower_id: int) -> None:
        with self._lock:
            self._entry(follower_id)["counts"]["fail"] += 1

    def to_json(self) -> bytes:
        with self._lock:
            followers = {}
            for fid, f in self.followers.items():
                lat = dict(f["latency"])
                if lat["minimum"] == float("inf"):  # failures only:
                    lat["minimum"] = 0.0  # keep the JSON RFC-valid
                followers[fid] = {"latency": lat,
                                  "counts": dict(f["counts"])}
            return json.dumps({
                "leader": f"{self.id:x}",
                "followers": followers,
            }).encode()
