"""Distributed multi-group server: G co-hosted raft groups replicated
across M HOSTS (one member slot per host) — SURVEY §5.8's two tiers
composed.

`MultiGroupServer` (multigroup.py) batches all M members in one
process and therefore shares process fate; THIS server is the
cross-host form the reference actually provides (a machine can die
and the cluster keeps serving, etcdserver/cluster_store.go:106-156):

- Each host runs ONE member slot of every group
  (raft/distmember.py — the same batched device ops as the fused
  runtime, applied to a single slot's [G] state).
- A replication round ships ONE binary frame per peer host
  (wire/distmsg.py: [G] prev_idx/prev_term/n_ents arrays + payload
  blobs) over HTTP POST — the reference's fire-and-forget peer
  transport (server.go:202-206) with the group axis batched.  A
  failed POST is a dropped message; progress resumes next round.
- Each host has its OWN WAL and snapshot dir: entries, ballots
  (term/vote — double-vote safety across restarts) and commit
  frontiers are fsynced before any response or ack leaves the host
  (the Ready contract, node.go:41-60).
- Slow or restarted followers catch up by normal append repair
  (reject → next_ = commit hint + 1) or, past the leader's
  compaction point, by pulling a full snapshot
  (GET /mraft/snapshot — the msgSnap analog as a pull).

Client writes go to the group's leader host (followers forward via
POST /mraft/propose); reads serve from any host's store replica.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

import numpy as np

from ..obs import metrics as _obs
from ..obs.devledger import ledger as _ledger
from ..obs.flight import FlightRecorder
from ..raft.distmember import DistMember
from ..snap import NoSnapshotError, Snapshotter
from ..snap.stream import (
    CHUNK_PATH as SNAP_CHUNK_PATH,
    FRONTIER_PATH as SNAP_FRONTIER_PATH,
    META_PATH as SNAP_META_PATH,
    ChunkPuller,
    SnapshotSource,
    SnapStreamError,
    SourceCache,
    StaleSourceError,
)
from ..store import Store
from ..utils import faults as _faults
from ..utils.backoff import Backoff
from .frontdoor import LISTEN_BACKLOG
from ..utils.errors import EtcdError, EtcdNoSpace
from ..utils.trace import tracer
from ..utils.wait import Chan, Wait
from ..wal import WAL, exist as wal_exist
from ..wire import Entry, GroupEntry, HardState, Snapshot
from ..wire.proto import marshal_group_entries
from ..wire import clientmsg
from ..wire import rolemsg
from ..wire.distmsg import (
    AppendBatch,
    AppendResp,
    FrameError,
    PackedPayloads,
    VoteReq,
    VoteResp,
    unmarshal_any,
)
from ..wire.requests import Info, Request
from .distpipe import AppendPipeline
from .multigroup import TICK_INTERVAL, group_of
from .peerlink import KeepAlivePool, PipeChannel
from .readindex import (
    PATH_SERIALIZABLE,
    LeaseClock,
    ReadQueue,
    WaitPoints,
    lease_drift_ticks,
    serve_counter,
)
from .server import (
    DEFAULT_SNAP_COUNT,
    Response,
    ServerStoppedError,
    UnknownMethodError,
    apply_request_to_store,
    gen_id,
)

log = logging.getLogger(__name__)

# Peer-tier read endpoints (PR 7 linearizable read path)
READ_INDEX_PATH = "/mraft/readindex"
GET_MANY_PATH = "/mraft/get_many"
ROLE_FWD_PATH = "/mraft/role_fwd"

# read_many result-slot sentinels: identity-compared module objects,
# never strings — a STORED VALUE equal to any string sentinel would
# collide with it (the compact fast path writes raw leaf values into
# the same result list)
_SERZ = object()     # serializable entry, serve after the linz pass
_EXPIRED = object()  # pending read dropped by the expiry sweep

# WAL record kinds (GroupEntry.kind)
K_ENTRY = 0      # a group's log entry
K_FRONTIER = 1   # commit-frontier marker: [G] commit + [G] terms
K_BALLOT = 2     # durable term/vote: [G] terms + [G] votes


class FrameDropped(Exception):
    """A peerlink.recv failpoint swallowed an inbound frame: the
    handler closes the connection without a response — to the sender
    this is a lost message (teardown + probe), to this host the
    frame never arrived."""


class _Pending:
    __slots__ = ("req", "data", "id", "retries", "group", "trace")

    def __init__(self, req, data, id, group=None, trace=None):
        self.req, self.data, self.id = req, data, id
        self.retries = 0
        # explicit group routing (ConfChange entries target a group
        # directly instead of hashing a client path)
        self.group = group
        # head-sampled distributed-trace id (PR 8; None = untraced)
        self.trace = trace


class DistServer:
    """Member ``slot`` of an M-host distributed multi-group cluster.

    ``peer_urls``: slot-indexed peer base URLs (this host's own slot
    entry is ignored); e.g. ``["http://127.0.0.1:7700", ...]``.
    """

    def __init__(self, data_dir: str, *, slot: int,
                 peer_urls: list[str], g: int = 64,
                 cap: int = 1024, name: str | None = None,
                 snap_count: int = DEFAULT_SNAP_COUNT,
                 max_batch_ents: int = 32,
                 tick_interval: float = TICK_INTERVAL,
                 sync_interval: float = 0.5,
                 post_timeout: float = 1.0,
                 election: int = 10,
                 storage_backend: str = "auto",
                 live: int | None = None,
                 client_urls: list[str] | None = None,
                 mesh=None, peer_tls=None,
                 pipeline_depth: int = 8,
                 coalesce_us: int = 2000,
                 coalesce_ents: int = 512,
                 coalesce_bytes: int = 1 << 20,
                 snap_keep: int | None = None,
                 lease_ticks: int | None = None):
        self.slot = slot
        self.g, self.m = g, len(peer_urls)
        # live member slots (< m leaves spare slots for runtime
        # AddMember; the extra peer URLs name the joinable hosts)
        self.live = self.m if live is None else live
        if not (0 < self.live <= self.m):
            # an out-of-range live count would silently make quorum
            # unattainable (nmembers is taken verbatim by the engine)
            raise ValueError(
                f"live={self.live} must be in 1..{self.m} "
                f"(len(peer_urls))")
        self.peer_urls = list(peer_urls)
        # Peer-tier TLS, same contexts as the classic sender/listener
        # (utils/transport.py; client-cert auth required when the
        # server context carries a CA)
        self._peer_ssl_srv = None
        self._peer_ssl_cli = None
        tls_on = peer_tls is not None and not peer_tls.empty()
        # scheme/TLS agreement up front: a mismatch would fail every
        # handshake SILENTLY (_post_peer treats errors as dropped
        # frames) — a dead cluster with nothing in the logs
        https = {u.startswith("https://") for u in self.peer_urls}
        if tls_on and https != {True}:
            raise ValueError(
                "peer TLS configured but --dist-peers has non-https "
                "URLs")
        if not tls_on and True in https:
            raise ValueError(
                "https --dist-peers requires peer TLS "
                "(--peer-cert-file/--peer-key-file)")
        if tls_on:
            self._peer_ssl_srv = peer_tls.server_context()
            self._peer_ssl_cli = peer_tls.client_context()
        if mesh is not None:
            # validate BEFORE any disk mutation: failing after the
            # fresh WAL is created would make the corrected retry
            # look like a restart (fresh=False) and skip bootstrap
            from ..parallel.mesh import check_group_divisible

            check_group_divisible(mesh, g)
        self.name = name or f"dist{slot}"
        self.snap_count = snap_count or DEFAULT_SNAP_COUNT
        self.tick_interval = tick_interval
        self.sync_interval = sync_interval
        self.post_timeout = post_timeout
        self.backend = storage_backend
        self.id = int.from_bytes(
            hashlib.sha1(self.name.encode()).digest()[:8],
            "big") & (2**63 - 1)

        self.store = Store()
        # watch fanout on its own delivery stage (PR 9):
        # _apply_committed runs under self.lock, so watcher-queue
        # work there would stall every handler and the round loop —
        # the engine thread takes it instead
        self.store.fanout.start()
        self.w = Wait()
        self.done = threading.Event()
        self.lock = threading.RLock()
        # serving seams the v2 HTTP layer mounts against (api/http.py
        # reads do/index/term/store/stats/cluster_store — the same
        # surface EtcdServer and MultiGroupServer expose)
        from .cluster import ClusterStore
        from .stats import LeaderStats, ServerStats

        self.server_stats = ServerStats(self.name, self.id)
        self.leader_stats = LeaderStats(self.id)
        self.cluster_store = ClusterStore(self.store)
        self._client_urls = client_urls or []
        self._queue: queue.Queue[_Pending | None] = queue.Queue()
        self._slot_ids: dict[int, int] = {}  # slot -> member id cache
        self._requeue: list[deque] = [deque() for _ in range(g)]
        self._need_pull = False      # snapshot catch-up requested
        # Streamed-install retry state (PR 6): a failed pull re-arms
        # _need_pull and backs off with jittered exponential delay
        # across attempts (capped) instead of silently dropping the
        # request — the wedge the monolithic pull had.  Guarded by
        # self.lock.  Since PR 10 the shape lives in the shared
        # utils/backoff.Backoff (site="snap_pull").
        self._pull_backoff = Backoff(base=max(0.25, post_timeout),
                                     cap=30.0, site="snap_pull")
        self._pull_not_before = 0.0  # monotonic gate for next attempt
        # per-donor store-size hints from the frontier probe: scales
        # the meta-fetch timeout with the blob the donor must
        # serialize before replying (round-loop/pull-thread only)
        self._donor_size_hint: dict[int, int] = {}
        # donor-side pinned snapshot serializations (chunk streams
        # must serve one immutable byte stream per pull).  keep
        # scales with the peer count: every OTHER member may lag
        # concurrently (partition heal), and each pull pins its own
        # stream — a fixed small keep would let them evict each
        # other's pins mid-stream into stale/backoff churn
        self._snap_sources = SourceCache(keep=max(2, self.m - 1))
        # corruption-injection test hook (chaos drill): flip one byte
        # of this chunk index the FIRST time it is served, proving
        # the receiver rejects + refetches rather than installs
        self._corrupt_chunk = int(os.environ.get(
            "ETCD_SNAP_STREAM_CORRUPT_CHUNK", -1))
        self._corrupted_once = False
        # snapshot-at-threshold runs on the ROUND LOOP, outside
        # self.lock (apply paths only raise this flag); _snap_mutex
        # serializes direct snapshot() callers against it
        self._want_snap = False
        self._snap_mutex = threading.Lock()
        # the deferred snapshot runs on its own thread (spawned and
        # tracked by the round loop only): save_snap's write+fsync of
        # a big store must not stall election ticks or leader pumps —
        # the round loop IS the heartbeat source
        self._snap_thread: threading.Thread | None = None
        # the streamed pull runs off the round loop too (spawned and
        # tracked by the round loop only): meta fetch + chunk stream
        # of a big store block for minutes, and the round loop is the
        # tick/heartbeat source for any lanes this host still leads
        self._pull_thread: threading.Thread | None = None
        # one source of truth for election forensics (liveness beat +
        # campaign-lost logging), read once at construction
        self._debug_elections = bool(
            os.environ.get("ETCD_DEBUG_ELECTIONS"))
        self._thread: threading.Thread | None = None
        self._httpd = None
        # Round-loop I/O plumbing that must NOT be rebuilt per round
        # (a fresh ThreadPoolExecutor + TCP connect per exchange cost
        # more than the frame transfer at localhost latencies): one
        # persistent worker pool for the vote round-trips and the
        # shared keep-alive connection cache (peerlink.KeepAlivePool,
        # also behind the classic sender) for every synchronous POST.
        from concurrent.futures import ThreadPoolExecutor

        self._xchg_pool = ThreadPoolExecutor(
            max_workers=max(1, self.m - 1),
            thread_name_prefix=f"dist{slot}-xchg")
        self._pool = KeepAlivePool(timeout=post_timeout,
                                   ssl_context=self._peer_ssl_cli)
        # read-index fetches ride their OWN keep-alive pool: the
        # leader's /mraft/readindex handler may lawfully hold the
        # request for up to 5s awaiting quorum confirmation
        # (fresh-leader window), while the shared pool's socket
        # timeout is post_timeout (1-2s) — over there a slow-but-
        # answering leader would read as unreachable, fail the read
        # no_leader, and tear down the pooled socket
        self._ri_pool = KeepAlivePool(
            timeout=max(6.0, 3.0 * post_timeout),
            ssl_context=self._peer_ssl_cli)

        # Windowed append pipeline (PR 5): per-peer (epoch, seq)
        # tagged in-flight frames over striped pipelined connections;
        # acks absorbed as they arrive on the channel reader threads
        # (quorum recomputed per ack).  All pipeline state below is
        # guarded by self.lock.
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth={pipeline_depth} must be >= 1 "
                f"(1 == lockstep-equivalent window)")
        self.pipe = AppendPipeline(self.m, slot, pipeline_depth)
        # the second striped connection parallelizes socket I/O and
        # follower-side processing ACROSS CORES; on a single-core
        # host it only fragments the [G]-wide frames (two half-frames
        # cost two full engine dispatches + two fsyncs at the
        # follower — measured 2526/s vs 3813/s on the loopback
        # bench), so striping gates on real parallelism being there
        self._n_stripes = (2 if pipeline_depth > 4
                           and (os.cpu_count() or 1) > 1 else 1)
        self._stripe_masks = [
            (np.arange(g) % self._n_stripes) == s
            for s in range(self._n_stripes)]
        self._channels: dict[int, PipeChannel] = {}
        # per-peer [G] commit vector last shipped (empty-frame dedup:
        # heartbeats go out on commit movement or cadence, not every
        # loop iteration)
        self._sent_commit = np.full((self.m, g), -1, np.int64)
        self._hb_interval = tick_interval
        # minimum entries for a SECOND (or later) in-flight frame
        # (see the anti-fragmentation comment in _pump_peer): two
        # full coalesce batches — an idle pipe sends immediately, an
        # already-busy pipe only adds frames that amortize their
        # fixed per-frame cost.  ETCD_DIST_MIN_FRAME overrides for
        # bench sweeps.
        self._min_frame_ents = max(1, int(os.environ.get(
            "ETCD_DIST_MIN_FRAME", 2 * coalesce_ents)))
        self.coalesce_us = coalesce_us
        self.coalesce_ents = coalesce_ents
        self.coalesce_bytes = coalesce_bytes
        # (group, gindex) -> _Pending for in-flight leader proposals;
        # acked at apply, failed on leadership loss (guarded by lock)
        self._assigned: dict[tuple[int, int], _Pending] = {}
        # frontier-record dedup: (commit, terms) last written
        self._fr_last: tuple[np.ndarray, np.ndarray] | None = None

        os.makedirs(data_dir, mode=0o700, exist_ok=True)
        self._snapdir = os.path.join(data_dir, "snap")
        os.makedirs(self._snapdir, mode=0o700, exist_ok=True)
        self._waldir = os.path.join(data_dir, "wal")
        crc_fn = None
        if storage_backend != "host":
            try:
                from ..ops.crc_kernel import auto_crc32c

                crc_fn = auto_crc32c
            except ImportError:
                pass
        from ..snap import DEFAULT_SNAP_KEEP

        self.ss = Snapshotter(
            self._snapdir, crc_fn=crc_fn,
            keep=snap_keep if snap_keep is not None
            else int(os.environ.get("ETCD_SNAP_KEEP",
                                    DEFAULT_SNAP_KEEP)))

        self.seq = 0
        self.applied = np.zeros(g, np.int64)
        self.raft_index = 0
        self.raft_term = 0
        self._snapi = 0
        self._ballot = (np.zeros(g, np.int32), np.full(g, -1, np.int32))

        # Leadership-transition trace (GET /mraft/leaders): per-group
        # wall time this host last WON a lane's election, the term it
        # won, the applied frontier at that moment, and the wall time
        # of the first apply that advanced past it (= the lane became
        # writable end-to-end on the server side).  Lets the chaos
        # drill decompose its client-observed kill->writable window
        # into election delay / commit-pipeline delay / client-probe
        # artifact (VERDICT r4 #3).  Cost: one [G] bool compare per
        # round; term fetch only on the (rare) transition.
        self._elected_at = np.zeros(g, np.float64)
        self._elected_term = np.zeros(g, np.int64)
        self._applied_at_elect = np.zeros(g, np.int64)
        self._first_apply_at = np.zeros(g, np.float64)
        self._prev_lead = np.zeros(g, bool)

        # obs seams (PR 2).  The ack-RTT clock stamps each proposal
        # at SEND (leader append + frame build, _leader_round) keyed
        # by (group, gindex); the apply loop pops it at quorum-ack →
        # apply, so the histogram measures consensus RTT — queue wait
        # before the round never enters it (VERDICT: dist ack p50
        # measured queue depth, not RTT).  Mutated only under
        # self.lock.
        self._ack_clock: dict[tuple[int, int], float] = {}
        self._m_ack = _obs.registry.histogram("etcd_ack_rtt_seconds")
        self._m_frames = _obs.registry.counter(
            "etcd_peer_send_frames_total", path="dist")
        self._m_send_rtt = _obs.registry.histogram(
            "etcd_peer_send_seconds", path="dist")
        self._m_send_fail = _obs.registry.counter(
            "etcd_peer_send_failures_total", path="dist")
        self._m_campaigns = _obs.registry.counter(
            "etcd_election_campaigns_total")
        self._m_wins = _obs.registry.counter(
            "etcd_election_wins_total")
        self._m_apply_s = _obs.registry.histogram(
            "etcd_apply_seconds")
        self._m_apply_n = _obs.registry.histogram(
            "etcd_apply_batch_entries")
        self._m_pending = _obs.registry.gauge(
            "etcd_pending_proposals")
        self._m_coalesce = _obs.registry.histogram(
            "etcd_dist_coalesce_entries")
        # per-peer in-flight gauges, cached like every other hot-path
        # handle (the labeled registry lookup costs a lock + key
        # build per call, and _set_inflight runs per ack/pump)
        self._m_inflight = {
            p: _obs.registry.gauge("etcd_dist_pipeline_inflight",
                                   peer=str(p))
            for p in range(self.m) if p != slot}
        self._m_inflight_ents = {
            p: _obs.registry.gauge(
                "etcd_dist_pipeline_inflight_entries", peer=str(p))
            for p in range(self.m) if p != slot}
        # PR 14: answer batch endpoints in the binary client framing
        # (wire/clientmsg.py) when the request advertises it via
        # Accept.  ETCD_WIRE_BINARY=0 simulates a JSON-only server —
        # the mixed-version arm of the negotiation compat tests.
        self.wire_binary = \
            os.environ.get("ETCD_WIRE_BINARY", "1") != "0"

        # -- linearizable read path (PR 7) ----------------------------
        # Lease band: the lease may only vouch for leadership while
        # NO follower the quorum heard from can have fired its
        # election timer — lease_ticks must sit strictly below the
        # election band minus a clock-drift margin (the same
        # invariant the static lease-band checker enforces at call
        # sites and flag tables; DistMember clamps election >= m, so
        # validate against the clamped value).  lease_ticks=0
        # disables the lease: every linearizable read then takes the
        # batched-ReadIndex confirmation.
        eff_election = max(election, self.m)
        drift = lease_drift_ticks(eff_election)
        if lease_ticks is None:
            lease_ticks = eff_election // 2
        if lease_ticks < 0:
            raise ValueError(f"lease_ticks={lease_ticks} < 0")
        if lease_ticks and lease_ticks >= eff_election - drift:
            raise ValueError(
                f"lease_ticks={lease_ticks} must be < election - "
                f"drift margin = {eff_election} - {drift}: a lease "
                f"that outlives the election band could serve reads "
                f"after a new leader commits")
        self._lease_s = lease_ticks * tick_interval
        self.lease = LeaseClock(g, self.m, slot)
        self._reads = ReadQueue(g)
        self._waits = WaitPoints(g)
        # current-term-commit gate (raft thesis §6.4): a fresh leader
        # must not serve reads at its (possibly stale) commit index
        # until an entry of ITS term commits — _read_ok[g] tracks
        # that off the frontier terms _persist already computes, and
        # _read_floor[g] is the commit index when it first held
        # (>= every index an older leader could have committed).
        self._read_ok = np.zeros(g, bool)
        self._read_floor = np.zeros(g, np.int64)
        # host caches the read hot path serves from (a device fetch
        # per GET would cost more than the read): leadership is
        # _prev_lead (refreshed each round), hint mirrors the round
        # loop's fetch, membership refreshes on conf change/install
        self._hint_np = np.full(g, -1, np.int64)
        self._read_nudge_t = 0.0
        self._wait_expire_at = 0.0  # wait-point sweep cadence gate
        # namespace -> group cache: group_of is a sha1 per call and
        # the read lane routes tens of thousands of keys/s over a
        # small working set of first path segments (bounded: cleared
        # wholesale if an adversarial key stream ever fills it)
        self._ns_groups: dict[str, int] = {}
        self._m_ri_batch = _obs.registry.histogram(
            "etcd_read_index_batch_size")
        self._m_read_rtt = _obs.registry.histogram(
            "etcd_read_rtt_seconds")
        self._read_ctrs: dict[tuple[str, str], object] = {}

        # -- gray-failure semantics (PR 10) ---------------------------
        # NOSPACE read-only mode: an EtcdNoSpace from any WAL/snap
        # writer flips _nospace; writes are rejected with errorCode
        # 405 while reads keep serving (leader lanes via the lease —
        # heartbeats need no WAL), and the round loop probes the
        # disk with backoff until space returns.  _held_recs carries
        # leader-side WAL records whose entries are already in the
        # engine log (frames may be in flight): they re-persist
        # FIRST on recovery so the leader's own durable ack is never
        # counted for an unpersisted entry.  Guarded by self.lock.
        self._nospace = False
        self._held_recs: list[Entry] | None = None
        # precomputed failpoint link labels: the recv seam runs per
        # pipelined ack and per inbound frame — two f-string
        # allocations per crossing would tax the no-faults common
        # case for nothing
        self._self_label = f"s{slot}"
        self._peer_labels = {p: f"s{p}" for p in range(self.m)}
        self._nospace_backoff = Backoff(base=0.25, cap=5.0,
                                        site="nospace_probe")
        self._nospace_probe_t = 0.0
        self._m_nospace = _obs.registry.gauge("etcd_nospace_active")
        # Check-quorum step-down: a leader whose inbound acks are
        # lost (one-way partition) must abdicate so its followers —
        # whose timers its still-delivered heartbeats keep resetting
        # — can elect a reachable leader.  A lane steps down when
        # its quorum ack basis (lease clock) is older than the FULL
        # worst-case election window, with a fresh-win grace
        # (_lead_since, monotonic).
        self._lead_since = np.zeros(g, np.float64)
        self._down_s = 2.0 * (2 * max(election, self.m)) \
            * tick_interval

        # -- tracing + flight recorder (PR 8) -------------------------
        # Per-server ring: in-process test clusters must not mix
        # three servers' events in one ring (the stitcher keys on the
        # node).  ETCD_TRACE_SAMPLE (head sampling 1-in-N; 0 = trace
        # off), ETCD_FLIGHT_RING (capacity) and ETCD_TRACE_SLOW_MS
        # (tail-capture threshold) are read by the recorder.
        self.flight = FlightRecorder(node=self.name, slot=slot)
        # fault activations land in this server's black box, and a
        # fail-stop dumps the ring before the process exits
        _faults.FAULTS.attach_sink(self.flight)
        # committed-stream tap for the role-split topology (PR 15):
        # server/roles.py attaches a CommitSink AFTER start() so
        # WAL-replay applies never reach the apply worker twice.
        # Called under self.lock with (group, gindex, payload) rows;
        # payload is the already-marshaled Request — the handoff
        # never re-marshals what raft just committed.
        # typed (string: roles.py would be a circular import) so
        # the concurrency model can follow sink.push -> ring.push
        self.commit_sink: "CommitSink | None" = None
        # (group, gindex) -> trace_id for in-flight TRACED proposals
        # (sampled subset of _ack_clock's keys; guarded by self.lock)
        self._trace_live: dict[tuple[int, int], int] = {}
        # (peer, seq) -> [[trace, origin], ...] for frames whose
        # trace block is in the channel queue: the peerlink on_sent
        # callback pops this (GIL-atomic) and stamps the flight
        # frame event at the actual socket write
        self._traced_send: dict[tuple[int, int], list] = {}

        self.mr = DistMember(g, self.m, slot, cap,
                             election=election,
                             max_batch_ents=max_batch_ents, seed=slot,
                             live=self.live)
        # fresh = brand-new data dir (callers gate bootstrap-only
        # actions like the slot-0 mass campaign on this, NOT on
        # is_leader() — leadership is volatile and always empty
        # after a restart)
        self.fresh = not wal_exist(self._waldir)
        if not self.fresh:
            self._restart()
        else:
            self.wal = WAL.create(self._waldir,
                                  Info(id=self.id).marshal())
            zero = np.zeros(g, np.int32).tobytes()
            self.wal.save(HardState(), [Entry(
                index=0, term=0,
                data=GroupEntry(kind=K_FRONTIER,
                                payload=zero + zero).marshal())])
        # intra-host scale-out: this host's [G] batch sharded over a
        # local device mesh (after restart seeding so the replayed
        # arrays get placed too)
        self.mesh = mesh
        if mesh is not None:
            self.mr.shard(mesh)
        self._refresh_member_cache()

    def _refresh_member_cache(self) -> None:
        """Host copy of the engine's [G, M] membership (call with
        self.lock held; init/restart call before the lock exists).
        The read path's quorum-basis math runs per GET — it must
        not pay a device fetch for arrays that change only on conf
        changes and snapshot installs."""
        st = self.mr.state
        self._members_np = np.asarray(st.members).astype(bool)
        self._nmembers_np = np.asarray(st.nmembers).astype(np.int64)

    # -- restart ----------------------------------------------------------

    def _restart(self) -> None:
        """Snapshot + WAL replay → store, frontier, AND the log tail.

        Unlike the fate-sharing co-hosted server (which may drop
        never-acked tails, multigroup.py:26-31), a distributed member
        MUST retain entries it acked to the leader even if they are
        not yet committed — the leader counts that ack toward quorum
        (Raft durability).  So the tail above the frontier is
        reconstructed into the engine log, and the persisted ballot
        (term/vote) is restored for double-vote safety.
        """
        g = self.g
        frontier = np.zeros(g, np.int64)
        fterms = np.zeros(g, np.int64)
        snap_index = 0
        applied_total = 0
        try:
            snap = self.ss.load()
        except NoSnapshotError:
            snap = None
        if snap is not None:
            blob = json.loads(snap.data.decode())
            if len(blob["frontier"]) != g:
                raise RuntimeError(
                    f"snapshot written with g={len(blob['frontier'])}"
                    f", not {g}")
            self.store.recovery(blob["store"].encode())
            frontier = np.asarray(blob["frontier"], np.int64)
            fterms = np.asarray(blob["terms"], np.int64)
            snap_index = blob["seq"]
            applied_total = blob.get("applied_total", 0)
        snap_frontier = frontier.copy()
        self.seq = snap_index

        from .gereplay import scan as ge_stream_scan, seed_log_arrays
        from .server import _replay_wal_raw

        self.wal, md, _hs, raw = _replay_wal_raw(
            self._waldir, snap_index, self.backend, stage="restart")
        info = Info.unmarshal(md or b"")
        if info.id != self.id:
            raise RuntimeError(
                f"unexpected server id {info.id:x}, want {self.id:x}")

        # array pass (gereplay): one native envelope sweep; frontier/
        # ballot = last record of their kind; winner dedup vectorized
        stream = ge_stream_scan(raw)
        if len(stream):
            self.seq = max(self.seq, int(stream.seq.max()))
        terms = np.zeros(g, np.int32)
        votes = np.full(g, -1, np.int32)
        fpos = stream.last_of_kind(K_FRONTIER)
        if fpos >= 0:
            v = np.frombuffer(stream.payload(fpos), np.int32)
            if v.size != 2 * g:
                raise RuntimeError(
                    f"data dir written with g={v.size // 2}, not {g}")
            # frontier records are monotonic in stream order: the
            # last one wins (newer than the snapshot too)
            frontier = v[:g].astype(np.int64)
            fterms = v[g:].astype(np.int64)
        bpos = stream.last_of_kind(K_BALLOT)
        if bpos >= 0:
            v = np.frombuffer(stream.payload(bpos), np.int32)
            terms = v[:g].copy()
            votes = v[g:2 * g].copy()

        # committed prefix → store, in (group, gindex) order
        winners = stream.winner_positions()
        committed = winners[
            (stream.gindex[winners] > snap_frontier[
                stream.group[winners]])
            & (stream.gindex[winners] <= frontier[
                stream.group[winners]])]
        committed = committed[np.lexsort(
            (stream.gindex[committed], stream.group[committed]))]
        applied_n = int(committed.size)
        conf_changes: list[tuple[int, Request]] = []
        for k in committed:
            payload = stream.payload(int(k))
            if not payload:
                continue
            r = Request.unmarshal(payload)
            if r.method == "CONFCHANGE":
                # engine-targeted: re-applies after seeding below
                conf_changes.append((int(stream.group[k]), r))
            else:
                apply_request_to_store(self.store, r)

        # engine seeding: compacted-at-frontier log + contiguous tail
        # (acked-but-uncommitted entries MUST survive — the leader
        # counted our ack toward quorum), rebuilt in arrays
        mr = self.mr
        import jax.numpy as jnp

        cap = mr.cap
        log_term, last, tail_pos = seed_log_arrays(
            stream, winners, frontier, fterms, g, cap)
        for k in tail_pos:
            payload = stream.payload(int(k))
            if payload:
                mr.payloads[int(stream.group[k])][
                    int(stream.gindex[k])] = payload
        terms = np.maximum(terms, fterms.astype(np.int32))
        fr = jnp.asarray(frontier, jnp.int32)
        st = mr.state._replace(
            term=jnp.asarray(terms), vote=jnp.asarray(votes),
            commit=fr, applied=fr, offset=fr,
            last=jnp.asarray(last, jnp.int32),
            log_term=jnp.asarray(log_term))
        if snap is not None and "members" in blob:
            msnap = np.asarray(blob["members"], bool)
            if msnap.shape[1] != self.m:
                raise RuntimeError(
                    f"snapshot has {msnap.shape[1]} member slots, "
                    f"this cluster has {self.m} (len(peer_urls))")
            mj = jnp.asarray(msnap)
            st = st._replace(
                members=mj, nmembers=mj.sum(axis=1).astype(jnp.int32))
        mr.state = st
        # committed ConfChanges in the replayed window re-apply on
        # the fresh engine (the snapshot's mask covers everything
        # below it)
        for gi, r in conf_changes:
            self._apply_conf_change(gi, r)
        self._ballot = (terms.copy(), votes.copy())
        self.applied = frontier.copy()
        self.raft_index = applied_total + applied_n
        self.raft_term = int(terms.max()) if g else 0
        self._snapi = self.raft_index
        log.info("dist[%d]: restart — %d replayed, %d applied, "
                 "tail up to %s", self.slot, len(stream), applied_n,
                 int(last.max()) if g else 0)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Bind the peer listener and start the round loop."""
        from ..obs import profiler as _profiler
        from ..obs import timeseries as _timeseries

        # always-on per-process observability (PR 17): the sampling
        # profiler and the windowed-delta ring behind
        # /mraft/obs/timeseries (idempotent; ETCD_PROFILE_HZ=0
        # disables the sampler — the overhead-gate off arm)
        _profiler.start_default()
        _timeseries.start_default()
        threading.Thread(target=self._publish, daemon=True).start()
        u = urlparse(self.peer_urls[self.slot])
        handler = _make_peer_handler(self)
        self._httpd = _PeerHTTPServer((u.hostname, u.port), handler)
        self._httpd.daemon_threads = True
        if self._peer_ssl_srv is not None:
            # handshake deferred to the per-connection worker thread
            # (first read triggers it): a stalled client must not
            # block accept() and with it ALL peer raft traffic; the
            # handler's socket timeout bounds the lazy handshake
            self._httpd.socket = self._peer_ssl_srv.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def _publish(self) -> None:
        """Register this member under /_etcd/machines THROUGH
        consensus (server.go:463-491's publish retry loop): a
        local-replica write would diverge from the other replicas, so
        the registration is an ordinary replicated PUT, retried until
        a leader exists to commit it."""
        from .cluster import (
            ATTRIBUTES_SUFFIX,
            RAFT_ATTRIBUTES_SUFFIX,
            Member,
        )

        m = Member(id=self.id, name=self.name,
                   peer_urls=[self.peer_urls[self.slot]],
                   client_urls=self._client_urls)
        pairs = [
            (m.store_key() + RAFT_ATTRIBUTES_SUFFIX,
             json.dumps(m.raft_attributes.to_dict())),
            (m.store_key() + ATTRIBUTES_SUFFIX,
             json.dumps(m.attributes.to_dict())),
        ]
        while not self.done.is_set():
            try:
                for path, val in pairs:
                    self.do(Request(method="PUT", id=gen_id(),
                                    path=path, val=val), timeout=5.0)
                return
            except Exception:
                self.done.wait(1.0)  # no leader yet; retry

    def stop(self) -> bool:
        """Stop the server.  Returns True on a clean stop; False when
        the round loop failed to exit within the join timeout — the
        WAL is then left open (a closed WAL would raise mid-save when
        the loop unwedges) and the data dir MUST NOT be reused by a
        new server in this process until the loop actually exits."""
        self.done.set()
        self._queue.put(None)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()  # release the port for rebinds
        loop_exited = True
        if self._thread is not None \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
            loop_exited = not self._thread.is_alive()
        if loop_exited:
            self._xchg_pool.shutdown(wait=False)
        # else: a wedged round loop still owns the pool — leave it up
        # so its next _exchange doesn't die on "cannot schedule new
        # futures after shutdown"; _exchange also guards on self.done.
        for chan in list(self._channels.values()):
            chan.close()  # fails in-flight frames; done-guard drops
        self._pool.close()
        self._ri_pool.close()
        self.store.fanout.close()
        _faults.FAULTS.detach_sink(self.flight)
        # a deferred snapshot may still hold _snap_mutex mid-save;
        # join it before closing the WAL (its cut/gc would raise on
        # a closed file).  Same wedge rule as the round loop: if it
        # won't exit, leave the WAL open.
        snap_t = self._snap_thread
        if snap_t is not None and snap_t.is_alive() \
                and snap_t is not threading.current_thread():
            snap_t.join(timeout=10)
            loop_exited = loop_exited and not snap_t.is_alive()
        # same rule for the deferred pull: its install does a WAL
        # save under self.lock (the puller aborts promptly once done
        # is set — the stream's abort hook polls it)
        pull_t = self._pull_thread
        if pull_t is not None and pull_t.is_alive() \
                and pull_t is not threading.current_thread():
            pull_t.join(timeout=10)
            loop_exited = loop_exited and not pull_t.is_alive()
        if loop_exited:
            with self.lock:
                self.wal.close()
        else:
            # the wedged loop may still _persist when it unwedges — a
            # closed WAL would raise mid-save.  Leaving it open is
            # safe for durability (every save() fsyncs, nothing is
            # buffered between saves) but the caller must not reuse
            # the data dir in-process: two appenders would interleave
            # one segment's CRC chain.
            log.warning("dist[%d]: stop(): round loop or deferred "
                        "snapshot still running after join timeout; "
                        "WAL left open — do not reuse this data dir "
                        "in-process", self.slot)
        return loop_exited

    # -- durability helpers (call with self.lock held) --------------------

    def _persist(self, ents: list[Entry],
                 frontier: bool = True) -> None:
        """WAL-append ``ents`` (+ a frontier marker) and fsync.

        An empty save whose frontier has not moved since the last
        recorded one is SKIPPED outright: at the pipeline's adaptive
        cadence the loop runs orders of magnitude more often than the
        lockstep round did, and an unconditional hardstate+frontier
        fsync per iteration would turn idle loops into fsync storms
        (nothing new is durable-worthy when neither entries nor the
        commit vector changed).

        NOSPACE (PR 10): while the server is in read-only mode an
        empty (frontier-only) save is SKIPPED — the frontier record
        is an optimization (restart replays from an older frontier
        and catches up), never worth failing for on a full disk.  A
        save that DOES fail with ``EtcdNoSpace`` rolls this method's
        own frontier seq allocation back (the WAL already rolled the
        file back; caller-allocated record seqs are the caller's to
        hold or roll back) and re-raises."""
        if self._nospace and not ents:
            return
        seq0 = self.seq
        fr0 = self._fr_last
        if frontier:
            commit = self.mr.commit_index().astype(np.int32)
            unchanged = (self._fr_last is not None
                         and np.array_equal(commit, self._fr_last[0]))
            if unchanged:
                if not ents:
                    return
                # terms AT the commit frontier are immutable while
                # the frontier itself hasn't moved — reuse the cached
                # gather instead of re-dispatching term_at per flush
                terms = self._fr_last[1]
            else:
                terms = self.mr.commit_terms().astype(np.int32)
                # current-term-commit gate for the read path: the
                # lane may serve lease/ReadIndex reads only once its
                # commit frontier carries an entry of the CURRENT
                # term (self._ballot[0] is the durable host copy of
                # term — every term transition persists through
                # _ballot_record before acting).  The floor pins the
                # commit index at the moment the gate first opened:
                # >= anything an earlier leader could have committed.
                ok = terms >= self._ballot[0]
                self._read_floor = np.where(
                    ok & ~self._read_ok, commit.astype(np.int64),
                    self._read_floor)
                self._read_ok = ok
            self._fr_last = (commit, terms)
            self.seq += 1
            ents = ents + [Entry(
                index=self.seq, term=self.raft_term,
                data=GroupEntry(
                    kind=K_FRONTIER,
                    payload=commit.tobytes() + terms.tobytes())
                .marshal())]
        try:
            self.wal.save(HardState(term=self.raft_term, vote=0,
                                    commit=self.seq), ents)
        except EtcdNoSpace:
            self.seq = seq0
            self._fr_last = fr0
            raise

    def _ballot_record(self) -> list[Entry]:
        """Allocate (seq-ordered) the ballot record for a changed
        term/vote, or [] when unchanged.  Allocation happens HERE so
        a caller that prepends this to its entry batch gets one
        seq-contiguous WAL write — out-of-order seqs (a later seq on
        disk before earlier ones reads as an index gap on restart)
        are structurally unrepresentable."""
        st = self.mr.state
        terms = np.asarray(st.term, np.int32)
        votes = np.asarray(st.vote, np.int32)
        if (np.array_equal(terms, self._ballot[0])
                and np.array_equal(votes, self._ballot[1])):
            return []
        self._ballot = (terms.copy(), votes.copy())
        # a term bump re-closes the read gate until an entry of the
        # new term commits (the fresh-leader stale-commit window)
        self._read_ok = (self._fr_last[1] >= terms
                         if self._fr_last is not None
                         else np.zeros(self.g, bool))
        self.raft_term = max(self.raft_term, int(terms.max()))
        self.seq += 1
        return [Entry(index=self.seq, term=self.raft_term,
                      data=GroupEntry(
                          kind=K_BALLOT,
                          payload=terms.tobytes() + votes.tobytes())
                      .marshal())]

    def _persist_ballot(self) -> None:
        """Durable term/vote BEFORE any vote or campaign leaves this
        host (the HardState analog, wal.go:35-39) — only when it
        actually changed.  ENOSPC rolls the allocation back and
        re-raises: an unpersisted ballot must never back a vote."""
        seq0 = self.seq
        ballot0 = self._ballot
        rec = self._ballot_record()
        if rec:
            try:
                self.wal.save(
                    HardState(term=self.raft_term, vote=0,
                              commit=self.seq), rec)
            except EtcdNoSpace:
                self.seq = seq0
                self._ballot = ballot0
                raise

    def _entry_records(self, gis, base, items) -> list[Entry]:
        """WAL records for entries appended at this host: one flat
        (group, gindex, gterm, payload) table batch-marshaled via
        ``marshal_group_entries`` — no per-record GroupEntry object
        (PR 14: the record builder was the propose path's top
        allocation line after the engine fusion)."""
        terms = self.mr.terms()
        groups: list[int] = []
        gindex: list[int] = []
        gterms: list[int] = []
        blobs: list[bytes] = []
        for gi in np.asarray(gis).tolist():
            b0, t = int(base[gi]), int(terms[gi])
            for j, p in enumerate(items[gi]):
                groups.append(gi)
                gindex.append(b0 + 1 + j)
                gterms.append(t)
                blobs.append(p.data)
        return self._seal_records(
            marshal_group_entries(K_ENTRY, groups, gindex, gterms,
                                  blobs))

    def _seal_records(self, datas: list[bytes]) -> list[Entry]:
        """Wrap batch-marshaled GroupEntry blobs in WAL Entries with
        one vectorized seq allocation."""
        self.seq += len(datas)
        seq0 = self.seq - len(datas)
        rt = self.raft_term
        return [Entry(index=seq0 + 1 + i, term=rt, data=d)
                for i, d in enumerate(datas)]

    def _frame_entry_records(self, msg: AppendBatch,
                             appended) -> list[Entry]:
        """WAL records for the entries an inbound frame appended.
        A packed frame (FLAG_PACKED) drives ONE flat pass over the
        validated entry table — mask by the accepting lanes, batch-
        marshal, done; the unpacked fallback walks per group."""
        if (msg.ent_group is not None
                and isinstance(msg.payloads, PackedPayloads)):
            groups = np.asarray(msg.ent_group)
            keep = np.nonzero(np.asarray(appended)[groups])[0]
            if not keep.size:
                return []
            gl = groups[keep]
            il = np.asarray(msg.ent_gindex)[keep]
            # ent_terms[g, j] with j = gindex - prev_idx[g] - 1;
            # in-range by the unmarshal-time table validation
            j = il - np.asarray(msg.prev_idx)[gl] - 1
            gterms = np.asarray(msg.ent_terms)[gl, j]
            flat = msg.payloads.flat
            return self._seal_records(marshal_group_entries(
                K_ENTRY, gl.tolist(), il.tolist(), gterms.tolist(),
                [flat[k] for k in keep.tolist()]))
        groups = []
        gindex = []
        gterms = []
        blobs = []
        for gi in np.nonzero(appended)[0].tolist():
            p0 = int(msg.prev_idx[gi])
            row = msg.payloads[gi]
            for j in range(int(msg.n_ents[gi])):
                groups.append(gi)
                gindex.append(p0 + 1 + j)
                gterms.append(int(msg.ent_terms[gi, j]))
                blobs.append(row[j])
        return self._seal_records(
            marshal_group_entries(K_ENTRY, groups, gindex, gterms,
                                  blobs))

    # -- peer RPC (HTTP handler entry points) -----------------------------

    def handle_frame(self, data: bytes) -> bytes:
        """POST /mraft: one batched consensus frame in, the response
        frame out.  Everything this host learned is durable before
        the response bytes leave (Ready contract ordering)."""
        t_recv = time.monotonic()
        with tracer.stage("dist.frame_unmarshal"):
            msg = unmarshal_any(data)
        # inbound half of an asymmetric partition (PR 10): the
        # [src->dst]-qualified peerlink.recv failpoint — a dropped
        # frame never touches engine state and gets NO response (the
        # handler closes the connection; to the sender it is a lost
        # message)
        sender = getattr(msg, "sender", None)
        try:
            act = _faults.hit(
                "peerlink.recv",
                src=self._peer_labels.get(sender),
                dst=self._self_label)
        except OSError as e:
            raise FrameDropped() from e
        if act == _faults.DROP:
            raise FrameDropped()
        traced = (isinstance(msg, AppendBatch) and msg.trace) or None
        if traced:
            # the receive edge of the stitcher's clock-alignment
            # pair, stamped BEFORE the lock (symmetric with the
            # leader's off-lock socket-write/ack stamps)
            self.flight.record(
                "frame", t=t_recv, dir="recv", src=msg.sender,
                seq=msg.seq, traces=[[t[2], t[3]] for t in traced])
        with self.lock, tracer.span("dist.handle_frame"):
            if self.done.is_set():
                # stop() closes the WAL under this lock with done
                # already set — refuse the frame BEFORE mutating
                # engine state (the handler turns this into a quiet
                # 503; the sender treats it as transport failure and
                # probes on reconnect)
                raise ServerStoppedError()
            if self._nospace:
                # read-only: appended entries could not be persisted
                # and votes could not record a durable ballot — both
                # are refused BEFORE any engine mutation (the
                # handler answers 507; the sender probes and the
                # at-least-once redelivery rebuilds everything once
                # space returns)
                raise EtcdNoSpace(
                    cause="member is read-only (NOSPACE)")
            if isinstance(msg, AppendBatch):
                self.server_stats.recv_append()
                with tracer.stage("dist.handle_append"), \
                        _ledger.dispatch("dist.handle_append"):
                    resp = self.mr.handle_append(msg)
                # the ballot record (if the term changed in this
                # frame) leads the batch: _ballot_record allocates
                # seqs in order, so one seq-contiguous WAL write
                # carries ballot + entries (a later seq on disk
                # before earlier ones reads as an index gap on the
                # next restart — found by the chaos drill)
                seq0 = self.seq
                ballot0 = self._ballot
                with tracer.stage("dist.frame_records"):
                    recs = self._ballot_record()
                    recs.extend(self._frame_entry_records(
                        msg, resp.appended))
                try:
                    with tracer.stage("dist.frame_persist"):
                        self._persist(recs)
                except EtcdNoSpace:
                    # full disk mid-frame: the engine appended but
                    # nothing hit the WAL (file rolled back).  Roll
                    # the seq/ballot allocations back, go read-only,
                    # and give the sender NO ack — its at-least-once
                    # redelivery re-persists these entries after
                    # recovery (duplicate engine appends are no-ops,
                    # duplicate WAL records dedup at replay).
                    self.seq = seq0
                    self._ballot = ballot0
                    self._enter_nospace("handle_frame persist")
                    raise
                if traced:
                    # one fsync covered the whole batch: every traced
                    # entry whose lane actually appended is durable
                    # on this follower as of NOW.  Lane index is
                    # bounds-checked — a malformed trace block must
                    # degrade to a missing span, never a handler 500.
                    t_sync = time.monotonic()
                    appended = resp.appended
                    for g_, gi_, tid, org in traced:
                        if appended is not None \
                                and 0 <= g_ < self.g \
                                and appended[g_]:
                            self.flight.span(tid, org,
                                             "follower_fsync",
                                             t=t_sync, host=self.slot)
                if bool(np.any(msg.need_snap & msg.active)):
                    if log.isEnabledFor(logging.DEBUG):
                        log.debug("dist[%d]: need_snap frame from %d "
                                  "lanes=%s", self.slot, msg.sender,
                                  np.nonzero(msg.need_snap
                                             & msg.active)[0].tolist())
                    self._need_pull = True
                with tracer.stage("dist.frame_apply"):
                    self._apply_committed()
                # echo the pipeline tags: the leader matches this ack
                # to its in-flight frame by (epoch, seq)
                resp.seq, resp.epoch = msg.seq, msg.epoch
                with tracer.stage("dist.frame_marshal_resp"):
                    out = resp.marshal()
                if traced:
                    self.flight.record("frame", dir="resp",
                                       src=msg.sender, seq=msg.seq)
                return out
            if isinstance(msg, VoteReq):
                resp = self.mr.handle_vote(msg)
                try:
                    self._persist_ballot()
                except EtcdNoSpace:
                    # the grant is NOT durable: never send it (a
                    # vote that could be forgotten across a restart
                    # is a double-vote waiting to happen) — go
                    # read-only and give the candidate nothing
                    self._enter_nospace("vote persist")
                    raise
                return resp.marshal()
        raise ValueError(f"unhandled frame {type(msg).__name__}")

    def handle_forward(self, data: bytes,
                       timeout: float) -> Response:
        """POST /mraft/propose: a follower-forwarded client write."""
        r = Request.unmarshal(data)
        return self.do(r, timeout=timeout, forward=False)

    def _snapshot_dict(self) -> dict:
        """The snapshot payload fields (call with self.lock held)."""
        return {
            "store": self.store.save().decode(),
            "frontier": [int(x) for x in self.applied],
            "terms": [int(x) for x in
                      self.mr.terms_at(self.applied).astype(int)],
            "seq": self.seq,
            "applied_total": self.raft_index,
            # per-group live-membership at the frontier:
            # conf changes below it need no entry replay
            "members": np.asarray(self.mr.state.members)
            .astype(int).tolist(),
        }

    def snapshot_blob(self) -> bytes:
        """GET /mraft/snapshot: the current store + frontier (what a
        lagging follower installs; kept as the legacy monolithic
        endpoint — diagnostics and the drill's frontier probe use
        it)."""
        with self.lock:
            d = self._snapshot_dict()
        return json.dumps(d).encode()

    def snapshot_frontier(self) -> bytes:
        """GET /mraft/snapshot/frontier: the applied vector alone —
        the receiver's cheap pre-pin dominance probe.  A meta pin
        serializes + CRC-chains the whole store under the lock and
        holds the blob pinned for the cache TTL; a donor that cannot
        dominate must never be made to pay that."""
        with self.lock:
            frontier = [int(x) for x in self.applied]
        # cheap size hint so the receiver can scale its meta-fetch
        # timeout with the donor's store size (the pin serializes a
        # blob of the same order as the newest durable snapshot; a
        # FIXED meta timeout wedges every pull of a store big enough
        # to out-serialize it — the chunk deadline is size-scaled
        # for the same reason)
        approx = 0
        try:
            newest = self.ss._snap_names()[0]
            approx = os.path.getsize(os.path.join(self.ss.dir, newest))
        except (NoSnapshotError, OSError):
            pass
        return json.dumps({"frontier": frontier,
                           "approx_bytes": approx}).encode()

    def snapshot_stream_meta(self) -> bytes:
        """POST /mraft/snapshot/meta: pin a fresh snapshot
        serialization and return its stream header (id, chunk CRC
        chain, frontier).  Each pull pins its own immutable byte
        stream — the live store mutates continuously, and chunk k
        and k+1 must come from ONE serialization."""
        with self.lock:
            d = self._snapshot_dict()
        payload = json.dumps(d).encode()
        extra = {k: d[k] for k in ("frontier", "terms", "seq",
                                   "applied_total", "members")}
        src = self._snap_sources.pin(
            SnapshotSource(payload, extra=extra))
        log.info("dist[%d]: pinned snapshot stream %s (%d bytes, "
                 "%d chunks)", self.slot, src.id, len(payload),
                 src.n_chunks)
        return json.dumps(src.meta()).encode()

    def snapshot_stream_chunk(self, body: bytes) -> tuple[int, bytes]:
        """POST /mraft/snapshot/chunk: serve one chunk of a pinned
        stream.  404 for an unknown/expired pin (the receiver
        refetches meta), 416 for an out-of-range index."""
        try:
            sid, k_s = body.decode().split()
            k = int(k_s)
        except ValueError:
            return 400, b""
        src = self._snap_sources.get(sid)
        if src is None:
            return 404, b""
        if not (0 <= k < src.n_chunks):
            return 416, b""
        data = src.chunk(k)
        # donor-side failpoint (PR 10): the generalized form of the
        # one-shot env corruption hook below
        try:
            act = _faults.hit("snapstream.serve",
                              src=f"s{self.slot}")
        except OSError:
            return 500, b""
        if act == _faults.DROP:
            return 503, b""
        if act == _faults.CORRUPT:
            data = _faults.flip_byte(data)
        if k == self._corrupt_chunk and not self._corrupted_once:
            # test hook: one corrupted serve, then clean — the
            # receiver must reject on the rolling CRC and refetch
            self._corrupted_once = True
            data = bytes(data[:-1]) + bytes([data[-1] ^ 0xFF])
            log.warning("dist[%d]: TEST HOOK corrupted snapshot "
                        "chunk %d on first serve", self.slot, k)
        return 200, data

    # -- client path ------------------------------------------------------

    # -- the write path's three verbs, shared by do()/do_many() -----------

    _WRITE_METHODS = ("POST", "PUT", "DELETE", "QGET", "CONFCHANGE")

    def _enqueue_write(self, r: Request, lead: np.ndarray):
        """Validate + register + enqueue one consensus-bound request.

        Returns ``("ch", ch)`` with the registered waiter channel,
        ``("not_leader", gi)`` when another host leads the group, or
        ``("err", exc)`` for an invalid request — the single copy of
        the write-side validation both do() and do_many() decode."""
        if r.id == 0:
            return "err", ValueError("r.id cannot be 0")
        if self._nospace:
            # NOSPACE read-only mode: every write (including a
            # would-be forward — this member's replica cannot apply
            # while it refuses frames, so read-your-write through it
            # would dangle) is rejected with the distinct code
            return "err", EtcdNoSpace(
                cause="member is read-only (NOSPACE)")
        if r.method == "GET" and r.quorum:
            r.method = "QGET"
        if r.method not in self._WRITE_METHODS:
            return "err", UnknownMethodError(r.method)
        try:
            gi = self._group_of_request(r)
        except ValueError as e:
            return "err", e
        if not lead[gi]:
            return "not_leader", gi
        ch = self.w.register(r.id)
        # head sampling at client ingest: the trace context is born
        # HERE and rides the _Pending through the coalescer, the
        # engine append, the DGB2 frames and the apply/ack path
        tid = self.flight.sample_trace()
        if tid is not None:
            self.flight.span(tid, self.slot, "ingest", group=gi)
        self._queue.put(_Pending(req=r, data=r.marshal(), id=r.id,
                                 group=gi, trace=tid))
        return "ch", ch

    def _await_ack(self, rid: int, ch,
                   timeout: float | None) -> Response | Exception:
        """Decode one waiter channel into a Response or the failure
        Exception (never raises — do() re-raises, do_many collects)."""
        try:
            x = ch.get(timeout=timeout)
        except queue.Empty:
            self.w.trigger(rid, None)
            return TimeoutError("request timed out")
        if x is None:
            return (ServerStoppedError() if self.done.is_set()
                    else TimeoutError("request dropped (no leader)"))
        if x.err is not None:
            return x.err
        return x

    def do(self, r: Request, timeout: float | None = None,
           forward: bool = True) -> Response:
        """Reference Do() semantics (server.go:337-380): writes and
        quorum reads through the group's consensus (forwarded to the
        leader host when that is not us); plain reads and watches
        from the local replica."""
        if r.method in self._WRITE_METHODS or \
                (r.method == "GET" and r.quorum):
            kind, v = self._enqueue_write(r, self.mr.is_leader())
            if kind == "err":
                raise v
            if kind == "not_leader":
                if not forward:
                    raise TimeoutError("not leader (no re-forward)")
                return self._forward(v, r.marshal(), timeout)
            x = self._await_ack(r.id, v, timeout)
            if isinstance(x, Exception):
                raise x
            return x
        if r.id == 0:
            raise ValueError("r.id cannot be 0")
        if r.method == "GET":
            if r.wait:
                wc = self.store.watch(r.path, r.recursive, r.stream,
                                      r.since)
                return Response(watcher=wc)
            if r.serializable:
                # explicit opt-out: the pre-PR-7 local-replica read,
                # possibly stale under partition — counted so bench
                # forensics can attribute it
                self._count_read(PATH_SERIALIZABLE, "ok")
                ev = self.store.get(r.path, r.recursive, r.sorted)
                return Response(event=ev)
            return self._linz_read(r, timeout)
        raise UnknownMethodError(r.method)

    def do_many(self, reqs: list[Request],
                timeout: float | None = None) -> list:
        """Pipelined batch of write requests: register + enqueue ALL
        of them, then collect acks — the proposals ride whatever
        replication rounds commit them, so one caller keeps many
        writes in flight instead of one lock-step write per
        round-trip (VERDICT r3 #5: client acks pipelined across
        rounds).  The reference gets the same effect from many
        concurrent HTTP clients (README.md:20 "benchmarked 1000s of
        writes/s"); here it is also a first-class batch API, the
        transport behind POST /mraft/propose_many.

        Returns a list aligned with ``reqs``: a Response where the
        write committed+applied, an Exception where it failed (the
        batch is NOT atomic — each entry commits independently)."""
        lead = self.mr.is_leader()
        chans: list[tuple[int, int, object]] = []
        out: list = [None] * len(reqs)
        seen: set[int] = set()
        for i, r in enumerate(reqs):
            if r.id in seen:
                # duplicate ids within one batch would share a waiter
                # channel and the second entry would read a false
                # failure — reject it up front
                out[i] = ValueError(f"duplicate id {r.id} in batch")
                continue
            seen.add(r.id)
            kind, v = self._enqueue_write(r, lead)
            if kind == "err":
                out[i] = v
            elif kind == "not_leader":
                out[i] = TimeoutError("not leader")
            else:
                chans.append((i, r.id, v))
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for i, rid, ch in chans:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            out[i] = self._await_ack(rid, ch, left)
        return out

    # -- linearizable read path (PR 7) ------------------------------------

    def _count_read(self, path: str, outcome: str, n: int = 1,
                    t0: float | None = None) -> None:
        """Serve accounting: the labeled counter (handle cached — a
        registry lookup per GET would cost a lock + key build), the
        store-stats per-path split on successful serves, and the
        register->serve RTT histogram."""
        key = (path, outcome)
        c = self._read_ctrs.get(key)
        if c is None:
            c = self._read_ctrs[key] = serve_counter(path, outcome)
        c.inc(n)
        if outcome == "ok":
            self.store.stats.inc_read_path(path, n)
        else:
            # every fail-closed read's CAUSE lands in the flight ring
            # (the linz drill's "why did reads reject" forensics)
            self.flight.record("read_fail", path=path,
                               outcome=outcome, n=n)
        if t0 is not None:
            dt = time.monotonic() - t0
            self._m_read_rtt.observe(dt)
            if dt > self.flight.slow_s:
                self.flight.record("tail", kind="slow_read",
                                   path=path, n=n,
                                   rtt_ms=round(dt * 1e3, 2))

    def _group_cached(self, path: str) -> int:
        """group_of with the namespace cache (read hot path)."""
        ns = path.strip("/").split("/", 1)[0]
        gi = self._ns_groups.get(ns)
        if gi is None:
            if len(self._ns_groups) >= 65536:
                self._ns_groups.clear()
            gi = self._ns_groups[ns] = group_of(path, self.g)
        return gi

    def _lease_fast_ok(self, gi: int, now: float) -> bool:
        """One group's lease check (call with self.lock held): the
        lane is led with a current-term commit applied, and a quorum
        endorsed this leadership within the lease window — the read
        serves NOW, no quorum round, no WAL."""
        if self._lease_s <= 0:
            return False
        if not self._read_ok[gi] \
                or self.applied[gi] < self._read_floor[gi]:
            return False
        b = self.lease.basis_one(gi, self._members_np,
                                 self._nmembers_np, now)
        return b + self._lease_s > now

    def _read_release(self, now: float | None = None) -> None:
        """Batched ReadIndex release sweep (call with self.lock
        held): ONE [G] quorum-basis computation confirms every
        pending read whose registration a completed quorum round (or
        a valid lease) now covers.  Rides the ack-absorb and round
        paths, so confirmation piggybacks on frames that were going
        out anyway."""
        if not self._reads.pending:
            return
        if now is None:
            now = time.monotonic()
        basis = self.lease.basis(self._members_np,
                                 self._nmembers_np, now)
        released = self._reads.release(
            lead=self._prev_lead, read_ok=self._read_ok,
            applied=self.applied, floor=self._read_floor,
            basis=basis, lease_until=basis + self._lease_s, now=now)
        if released:
            # weight by the reads riding each registration: a
            # read_many batch shares one channel per group (PR 14)
            self._m_ri_batch.observe(
                sum(pr.n for pr, _path, _rd in released))
            for pr, path, rd in released:
                pr.ch.close((path, rd))

    def _nudge_reads(self, now: float) -> None:
        """A read registered without lease cover (call with
        self.lock held): arm one out-of-cadence heartbeat per
        stripe (see _pump_peer) and poke the round loop so the
        confirmation round leaves promptly instead of at the next
        tick boundary.  The poke dedups at 1 ms so a single-read
        burst during a leaderless window can't flood the queue with
        wakes (each registered read would otherwise add one)."""
        if now - self._read_nudge_t > 0.001:
            self._queue.put(None)  # drain treats None as a bare wake
        self._read_nudge_t = now

    def _await_read(self, ch: Chan, timeout: float | None,
                    path_hint: str, t0: float):
        """Block on a registered read's channel; returns the
        ``(path, rd)`` confirmation or raises the fail-closed
        error."""
        try:
            x = ch.get(timeout=timeout)
        except queue.Empty:
            self._count_read(path_hint, "timeout")
            raise TimeoutError(
                "linearizable read timed out (no quorum "
                "confirmation)") from None
        if x is _EXPIRED:
            # the server-side expiry sweep dropped us (pathological
            # confirmation stall) — its own outcome label, NOT
            # not_leader: leadership may be fine
            self._count_read(path_hint, "expired")
            raise TimeoutError(
                "linearizable read expired server-side awaiting "
                "confirmation")
        if x is None:
            if self.done.is_set():
                self._count_read(path_hint, "stopped")
                raise ServerStoppedError()
            self._count_read(path_hint, "not_leader")
            raise TimeoutError(
                "leadership lost before the read confirmed")
        return x

    def _linz_read(self, r: Request,
                   timeout: float | None) -> Response:
        """Default-consistency GET: linearizable without touching
        the WAL.  Leader lanes serve under the lease (zero extra
        messages) or via the batched ReadIndex queue; follower lanes
        fetch a confirmed index from the leader and park on a local
        commit-index wait-point.  Every failure path is CLOSED — a
        read is never served from state a quorum may have
        overwritten."""
        t0 = time.monotonic()
        gi = self._group_cached(r.path)
        ch = None
        path = "lease"
        with self.lock:
            if self.done.is_set():
                raise ServerStoppedError()
            led = bool(self._prev_lead[gi])
            if led:
                if not self._lease_fast_ok(gi, t0):
                    ch = Chan()
                    self._reads.register(gi, t0,
                                         int(self.applied[gi]), ch)
                    self._nudge_reads(t0)
            else:
                leader = int(self._hint_np[gi])
        if not led:
            return self._follower_read(r, gi, leader, t0, timeout)
        if ch is not None:
            path = self._await_read(ch, timeout, "read_index", t0)[0]
        self._count_read(path, "ok", t0=t0)
        ev = self.store.get(r.path, r.recursive, r.sorted)
        return Response(event=ev)

    def _follower_read(self, r: Request, gi: int, leader: int,
                       t0: float,
                       timeout: float | None) -> Response:
        """Follower half: leader-confirmed read index + local apply
        wait-point, then serve from THIS replica (read traffic never
        ships the value over the peer tier, only the index)."""
        if leader < 0 or leader == self.slot:
            self._count_read("follower_wait", "no_leader")
            raise TimeoutError(
                "no leader known for linearizable read")
        rd = self._fetch_read_index(leader, gi)
        ch = None
        with self.lock:
            if self.done.is_set():
                raise ServerStoppedError()
            if self.applied[gi] < rd:
                ch = Chan()
                self._waits.register(gi, rd, ch,
                                     t0=time.monotonic())
        if ch is not None:
            try:
                x = ch.get(timeout=timeout)
            except queue.Empty:
                self._count_read("follower_wait", "timeout")
                raise TimeoutError(
                    "linearizable read timed out awaiting "
                    "replication") from None
            if x is _EXPIRED:
                self._count_read("follower_wait", "expired")
                raise TimeoutError(
                    "linearizable read expired awaiting "
                    "replication")
            if x is None:
                self._count_read("follower_wait", "stopped")
                raise ServerStoppedError()
        self._count_read("follower_wait", "ok", t0=t0)
        ev = self.store.get(r.path, r.recursive, r.sorted)
        return Response(event=ev)

    def _fetch_read_index(self, leader: int, gi: int) -> int:
        """POST /mraft/readindex to the group's leader over the
        DEDICATED read-index keep-alive pool (``_ri_pool`` — its
        socket timeout clears the leader's lawful 5s confirmation
        hold, which the shared pool's 1-2s timeout would misread as
        an unreachable leader); returns the confirmed index or
        raises (fail closed)."""
        body = json.dumps({"group": int(gi)}).encode()
        out = self._ri_pool.post(leader, self.peer_urls[leader],
                                 READ_INDEX_PATH, body)
        if out is None or out[0] != 200:
            self._count_read("follower_wait", "no_leader")
            raise TimeoutError("read-index fetch failed "
                               "(leader unreachable)")
        try:
            d = json.loads(out[1].decode())
            if "rd" not in d:
                self._count_read("follower_wait", "not_leader")
                raise TimeoutError(
                    f"read-index refused: {d.get('err')}")
            return int(d["rd"])
        except (ValueError, TypeError):
            self._count_read("follower_wait", "no_leader")
            raise TimeoutError(
                "read-index reply unparseable") from None

    def read_index(self, gi: int,
                   timeout: float | None = None) -> int:
        """Leader service behind POST /mraft/readindex: an apply
        index ``rd`` such that any replica serving at local
        ``applied >= rd`` observes every write acked before this
        call — the lease answers instantly, otherwise the request
        joins the batched ReadIndex queue like any local read."""
        if not (0 <= gi < self.g):
            raise ValueError(f"group {gi} out of range 0..{self.g}")
        t0 = time.monotonic()
        with self.lock:
            if self.done.is_set():
                raise ServerStoppedError()
            if not self._prev_lead[gi]:
                raise TimeoutError("not leader")
            if self._lease_fast_ok(gi, t0):
                return max(int(self.applied[gi]),
                           int(self._read_floor[gi]))
            ch = Chan()
            self._reads.register(gi, t0, int(self.applied[gi]), ch,
                                 kind="rd")
            self._nudge_reads(t0)
        return int(self._await_read(ch, timeout, "read_index",
                                    t0)[1])

    def _serve_read(self, path: str, r: Request | None):
        """One local store serve; EtcdError (e.g. key-not-found) is
        a per-entry result, not a batch failure.  Path-string
        entries (the compact get_many form) come back as the raw
        leaf VALUE via the store's Event-free fast lane — at the
        batch lane's read rates the Event allocation is the
        dominant per-read cost."""
        try:
            if r is None:
                return self.store.get_value(path)
            return Response(event=self.store.get(
                path, r.recursive, r.sorted))
        except EtcdError as e:
            return e

    def read_many(self, reqs: list,
                  timeout: float | None = None) -> list:
        """Batched read path (the GET analog of do_many, behind
        POST /mraft/get_many).  Entries are plain path strings (the
        compact wire form — a linearizable read's cost should be
        its key, not a protobuf decode) or full GET Requests.

        The hot shape is one lock take for the whole batch: lanes
        whose lease vouches serve via a per-group cached lease
        check — no per-read channel, no queue — and the rest
        register and ride ONE release sweep, so a whole batch
        confirms against one [G] basis compare (the amortization
        etcd_read_index_batch_size records).  Follower lanes share
        one read-index fetch per group.  Returns a list aligned
        with ``reqs``: Response or Exception per entry."""
        out: list = [None] * len(reqs)
        t0 = time.monotonic()
        linz: list[tuple[int, str, Request | None]] = []
        for i, r in enumerate(reqs):
            if isinstance(r, str):
                linz.append((i, r, None))
            elif r.method != "GET" or r.wait or r.quorum:
                # quorum (through-the-log) reads and non-reads take
                # their own paths; the batch endpoint is the
                # zero-WAL lane
                out[i] = UnknownMethodError(
                    f"get_many accepts plain GETs, not "
                    f"{r.method}{'?quorum' if r.quorum else ''}")
            elif r.serializable:
                out[i] = _SERZ
            else:
                linz.append((i, r.path, r))
        fast: list[tuple[int, str, Request | None]] = []
        # ONE Chan + ONE queue registration per GROUP, not per read:
        # the group's confirmation covers every read that registered
        # under it, and the stage tables flagged the per-read Chan
        # as the register loop's top allocation (PR 14 hoist)
        group_chans: dict[int, tuple[Chan, object, list]] = {}
        followers: dict[int,
                        list[tuple[int, str, Request | None]]] = {}
        if linz:
            with self.lock:
                if self.done.is_set():
                    raise ServerStoppedError()
                now = time.monotonic()
                lease_cache: dict[int, bool] = {}
                for i, path, r in linz:
                    gi = self._group_cached(path)
                    ok = lease_cache.get(gi)
                    if ok is None:
                        ok = bool(self._prev_lead[gi]) \
                            and self._lease_fast_ok(gi, now)
                        lease_cache[gi] = ok
                    if ok:
                        fast.append((i, path, r))
                    elif self._prev_lead[gi]:
                        ent = group_chans.get(gi)
                        if ent is None:
                            ch = Chan()
                            pr = self._reads.register(
                                gi, t0, int(self.applied[gi]), ch)
                            ent = group_chans[gi] = (ch, pr, [])
                        else:
                            ent[1].n += 1
                        ent[2].append((i, path, r))
                    else:
                        followers.setdefault(gi, []).append(
                            (i, path, r))
                if fast:
                    # the batch IS a confirmation sweep: one lease
                    # check per group released this many reads
                    self._m_ri_batch.observe(len(fast))
                if group_chans:
                    self._read_release(now)
                    if self._reads.pending:
                        self._nudge_reads(now)
        if fast:
            self._count_read("lease", "ok", n=len(fast))
            # batch-granular RTT sample: every read in the batch
            # shared this register->serve window
            self._m_read_rtt.observe(time.monotonic() - t0)
            plain = [(i, path) for i, path, r in fast if r is None]
            if plain:
                # one world-lock take + one stats update for the
                # whole compact batch
                for (i, _p), v in zip(plain, self.store.get_values(
                        [p for _i, p in plain])):
                    out[i] = v
            for i, path, r in fast:
                if r is not None:
                    out[i] = self._serve_read(path, r)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        served: dict[str, int] = {}
        for _gi, (ch, _pr, items) in group_chans.items():
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            try:
                p = self._await_read(ch, left, "read_index", t0)[0]
            except (TimeoutError, ServerStoppedError) as e:
                for i, _path, _r in items:
                    out[i] = e
                continue
            # the group's one confirmation covers its whole batch
            served[p] = served.get(p, 0) + len(items)
            for i, path, r in items:
                out[i] = self._serve_read(path, r)
        for p, n in served.items():
            self._count_read(p, "ok", n=n)
        if served:
            self._m_read_rtt.observe(time.monotonic() - t0)
        for i, r in ((i, r) for i, r in enumerate(reqs)
                     if out[i] is _SERZ):
            self._count_read(PATH_SERIALIZABLE, "ok")
            out[i] = self._serve_read(r.path, r)
        def _one_follower_group(gi: int, items) -> None:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            i0, path0, r0 = items[0]
            try:
                out[i0] = self._follower_read(
                    r0 if r0 is not None
                    else Request(method="GET", id=1, path=path0),
                    gi, int(self._hint_np[gi]), t0, left)
                # the confirmed wait already covers the rest of the
                # group's batch: serve them straight off the replica
                if len(items) > 1:
                    self._count_read("follower_wait", "ok",
                                     n=len(items) - 1)
                    for i, path, r in items[1:]:
                        out[i] = self._serve_read(path, r)
            except (TimeoutError, ServerStoppedError) as e:
                for i, _path, _r in items:
                    out[i] = e

        if len(followers) == 1:
            gi, items = next(iter(followers.items()))
            _one_follower_group(gi, items)
        elif followers:
            # groups are independent (one index fetch + wait each):
            # run them concurrently so batch latency is the SLOWEST
            # group's confirmation, not the sum over groups — each
            # group writes disjoint out[] slots
            ths = [threading.Thread(target=_one_follower_group,
                                    args=(gi, items))
                   for gi, items in followers.items()]
            for th in ths:
                th.start()
            for th in ths:
                th.join()
        return out

    def _group_of_request(self, r: Request) -> int:
        """Explicit group for engine-targeted entries (a CONFCHANGE's
        path encodes its group — hashing it like a client path would
        route the change to the wrong group's log); namespace hash
        for everything else."""
        if r.method == "CONFCHANGE":
            try:
                gi = int(r.path.rsplit("/", 1)[-1])
            except ValueError:
                raise ValueError(
                    f"malformed CONFCHANGE path {r.path!r}") from None
            if not (0 <= gi < self.g):
                # negative values would silently wrap to another
                # group's log via sequence indexing
                raise ValueError(
                    f"CONFCHANGE group {gi} out of range 0..{self.g}")
            return gi
        return group_of(r.path, self.g)

    def _forward(self, gi: int, data: bytes,
                 timeout: float | None) -> Response:
        """Forward a write to the group's leader host and surface its
        result as a store re-read (the event applied there reaches
        our replica via replication; the authoritative response body
        is re-served locally once our replica catches up)."""
        lead = int(self.mr.leader_hint()[gi])
        if lead < 0 or lead == self.slot:
            raise TimeoutError("no leader for group")
        url = self.peer_urls[lead] + "/mraft/propose"
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or 5.0,
                    context=self._peer_ssl_cli) as resp:
                body = resp.read()
        except (urllib.error.URLError, OSError) as e:
            raise TimeoutError(f"forward failed: {e}") from None
        d = json.loads(body.decode())
        if not d.get("ok"):
            from ..utils.errors import EtcdError

            raise EtcdError(d.get("errorCode", 300),
                            d.get("message", "forwarded propose "
                                             "failed"), d.get("cause"))
        from ..store.event import Event

        return Response(event=Event.from_dict(d["event"])
                        if d.get("event") else None)

    # -- the round loop ---------------------------------------------------

    def run(self) -> None:
        next_tick = time.monotonic() + self.tick_interval
        next_sync = time.monotonic() + self.sync_interval
        batch: list[_Pending] = []
        next_beat = 0.0  # ETCD_DEBUG_ELECTIONS liveness heartbeat
        while not self.done.is_set():
            if self._debug_elections and \
                    time.monotonic() >= next_beat:
                next_beat = time.monotonic() + 2.0
                st = self.mr.state
                log.info(
                    "dist[%d]: beat roles=%s elapsed=%s timeout=%s "
                    "lead=%s term=%s commit=%s last=%s offset=%s "
                    "next=%s match=%s", self.slot,
                    np.asarray(st.role)[:8].tolist(),
                    np.asarray(st.elapsed)[:8].tolist(),
                    np.asarray(st.timeout)[:8].tolist(),
                    np.asarray(st.lead)[:8].tolist(),
                    np.asarray(st.term)[:8].tolist(),
                    np.asarray(st.commit)[:8].tolist(),
                    np.asarray(st.last)[:8].tolist(),
                    np.asarray(st.offset)[:8].tolist(),
                    np.asarray(st.next_)[:4].tolist(),
                    np.asarray(st.match)[:4].tolist())
            batch = self._drain(timeout=min(
                self.tick_interval,
                max(next_tick - time.monotonic(), 0.001)))
            if self.done.is_set():
                break
            now = time.monotonic()
            if now >= next_sync:
                # TTL expiry must be REPLICATED, not leader-local: a
                # follower's replica would otherwise keep expired
                # keys forever.  The reference's leader SYNC proposal
                # (server.go:438-456) rides group 0's log here; every
                # host expires at that entry's apply.  (Cross-group
                # apply order is not globally serialized, so expiry
                # interleaving vs OTHER groups' writes can differ per
                # host by up to one sync interval — the co-hosted
                # server documents the same class of divergence.)
                if self.mr.is_leader()[0] and not self._nospace:
                    r = Request(method="SYNC", id=gen_id(),
                                time=int(time.time() * 1e9))
                    self._queue.put(_Pending(req=r, data=r.marshal(),
                                             id=r.id, group=0))
                next_sync = now + self.sync_interval
            if now >= next_tick:
                # WALL-CLOCK ticking: when a loop iteration overran
                # (CPU contention, a slow exchange), credit every
                # missed tick instead of silently dropping it — a
                # counted-ticks timer stretches the 1-2s election
                # timeout to tens of seconds under load (observed as
                # 15s leaderless windows in the batch chaos drill).
                # The reference's timers are real-time (server.go:182
                # time.Ticker).  Burst bounded: past 4x the worst-case
                # timeout nothing new can fire.
                behind = min(int((now - next_tick)
                                 / self.tick_interval) + 1,
                             8 * self.mr.election)
                next_tick += behind * self.tick_interval
                if next_tick < now:  # deep pause: resync the phase
                    next_tick = now + self.tick_interval
                with self.lock:
                    fire = self.mr.tick()
                    for _ in range(behind - 1):
                        fire = fire | self.mr.tick()
                    # a follower hearing appends has elapsed reset;
                    # lanes that fire lost their leader
                if fire.any():
                    self._campaign(fire)
            if self._nospace \
                    and time.monotonic() >= self._nospace_probe_t:
                self._nospace_recover()
            with self.lock:
                # handle_frame sets the flag under the lock; an
                # unlocked test-and-clear here could lose a pull
                # request that lands between the read and the write.
                # The backoff gate (_arm_pull_retry) spaces attempts
                # after failures — the flag itself is NEVER dropped
                # on failure, only deferred.
                need_pull = (self._need_pull
                             and time.monotonic()
                             >= self._pull_not_before
                             and (self._pull_thread is None
                                  or not self._pull_thread.is_alive()))
                if need_pull:
                    self._need_pull = False
            if need_pull:
                # off the round loop (same rule as the deferred
                # snapshot below): the meta fetch + chunk stream of a
                # big store block for minutes, and this thread is the
                # tick/heartbeat source — an inline pull would cost
                # leadership of every lane this host still leads
                self._pull_thread = threading.Thread(
                    target=self._pull_snapshot_bg,
                    name=f"dist{self.slot}-pull", daemon=True)
                self._pull_thread.start()
            self._leader_round(batch)
            # follower wait-point expiry lives HERE, not in
            # _leader_round: a pure follower's round returns early
            # there, yet IT is the host that parks wait-points.
            # Coarse cadence — the sweep is an O(pending) scan.
            if self._waits.pending \
                    and time.monotonic() >= self._wait_expire_at:
                self._wait_expire_at = time.monotonic() + 10.0
                with self.lock:
                    expired_waits = self._waits.expire(
                        time.monotonic(),
                        max(35.0, 8.0 * self.post_timeout))
                for ch in expired_waits:
                    ch.close(_EXPIRED)
            with self.lock:
                # apply paths raise the flag under the lock; clear it
                # under the lock too so a set landing between the read
                # and the write can't be lost.  While a deferred
                # snapshot is still running the flag stays SET (the
                # in-flight save captured an older seq; the trigger
                # re-fires once it finishes).
                want_snap = (self._want_snap
                             and (self._snap_thread is None
                                  or not self._snap_thread.is_alive()))
                if want_snap:
                    self._want_snap = False
            if want_snap:
                # off the round loop: save_snap's write+fsync of a
                # big store would stall ticks/heartbeats here long
                # enough to lose leadership on every big snapshot
                self._snap_thread = threading.Thread(
                    target=self._snapshot_bg,
                    name=f"dist{self.slot}-snap", daemon=True)
                self._snap_thread.start()

        for p in batch:
            self.w.trigger(p.id, None)
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            if p is not None:
                self.w.trigger(p.id, None)
        for q in self._requeue:
            while q:
                self.w.trigger(q.popleft().id, None)
        with self.lock:
            assigned = list(self._assigned.values())
            self._assigned.clear()
            pending_reads = self._reads.fail_all()
            pending_waits = self._waits.fail_all()
        for p in assigned:
            self.w.trigger(p.id, None)
        for pr in pending_reads:
            pr.ch.close(None)
        for ch in pending_waits:
            ch.close(None)

    def _drain(self, timeout: float) -> list[_Pending]:
        """Adaptive-cadence coalescing drain: after the first
        proposal arrives, keep collecting until the coalesce-entry /
        coalesce-byte threshold is reached or the ``coalesce_us``
        timer fires — whichever first (the fixed-round-tick batch
        boundary is gone; a lone write flushes in ~coalesce_us, a
        burst flushes as soon as it fills a batch)."""
        out: list[_Pending] = []
        try:
            p = self._queue.get(timeout=timeout)
        except queue.Empty:
            return out
        if p is None:
            return out
        out.append(p)
        nbytes = len(p.data)
        deadline = time.monotonic() + self.coalesce_us * 1e-6
        while (len(out) < self.coalesce_ents
               and nbytes < self.coalesce_bytes):
            left = deadline - time.monotonic()
            if left <= 0:
                break
            try:
                p = self._queue.get(timeout=left)
            except queue.Empty:
                break
            if p is None:
                break
            out.append(p)
            nbytes += len(p.data)
        self._m_coalesce.observe(len(out))
        return out

    def _leader_round(self, batch: list[_Pending]) -> None:
        """One pipelined leader stage: drain → append → frames OUT →
        own fsync (overlapped with the in-flight sends) → self-ack →
        commit/apply.

        This is the lockstep round (drain → append → persist →
        exchange → absorb → commit, server.go:247-323) decomposed:
        the synchronous ``_exchange`` barrier is gone — append frames
        are enqueued on the per-peer pipelined channels and their
        acks absorb OUT of band (``_absorb_ack``, on the channel
        reader threads) as they arrive, recomputing quorum commit per
        ack, so a slow follower no longer gates the fast pair and
        this stage never blocks on the network.  Durability overlap:
        the frames leave BEFORE the local WAL fsync runs, and the
        leader's own ack joins the quorum only when that fsync lands
        (``mr.ack_self``) — commit still requires a quorum of DURABLE
        copies, they just become durable in parallel now."""
        mr = self.mr
        if self._nospace:
            # read-only: reject the drained batch AND anything
            # requeued with the typed code (waiters get a decodable
            # EtcdNoSpace, never a silent timeout; proposing would
            # only grow the engine log with entries the WAL cannot
            # take)
            err = EtcdNoSpace(cause="member is read-only (NOSPACE)")
            for p in batch:
                self.w.trigger(p.id, Response(err=err))
            batch = []
            for q in self._requeue:
                while q:
                    self.w.trigger(q.popleft().id,
                                   Response(err=err))
        with self.lock:
            now_m = time.monotonic()
            if self._prev_lead.any():
                # check-quorum step-down (PR 10): a lane whose
                # quorum ack basis is older than the FULL worst-case
                # election window cannot be committing anything, yet
                # its outbound heartbeats may still be muzzling the
                # followers' timers (one-way partition).  Abdicate
                # so a reachable leader can be elected; the normal
                # lost_lead machinery below observes the transition.
                basis = self.lease.basis(self._members_np,
                                         self._nmembers_np, now_m)
                stale = self._prev_lead & (
                    np.maximum(basis, self._lead_since)
                    < now_m - self._down_s)
                if stale.any():
                    mr.step_down(stale)
                    self.flight.record(
                        "step_down", lanes=int(stale.sum()),
                        first=np.nonzero(stale)[0][:8].tolist(),
                        cause="check_quorum")
                    log.warning(
                        "dist[%d]: check-quorum step-down on %d "
                        "lane(s): no quorum ack for %.1fs",
                        self.slot, int(stale.sum()), self._down_s)
            # backstop: a frame whose ack AND failure were both lost
            # (transport edge cases) must not pin the window shut
            expired = self.pipe.expire(time.monotonic(),
                                       8.0 * self.post_timeout)
            for peer, metas in expired.items():
                _obs.registry.counter("etcd_dist_frame_resend_total",
                                      reason="expired").inc(len(metas))
                mr.probe_reset(peer)
                self._set_inflight(peer)
            lead = mr.is_leader()
            won = lead & ~self._prev_lead
            lost_lead = self._prev_lead & ~lead
            if won.any() or lost_lead.any():
                # leadership set changed: every in-flight frame
                # belongs to the old reign — drop them and let their
                # late acks read stale_epoch
                dropped = self.pipe.bump_epoch()
                self._traced_send.clear()  # old reign's send stamps
                if dropped:
                    _obs.registry.counter(
                        "etcd_dist_frame_resend_total",
                        reason="stale_epoch").inc(dropped)
            if lost_lead.any():
                # black-box forensics: a deposed lane also loses its
                # lease cover — this event is what lets the stitcher
                # and the drill see WHY reads started failing closed
                self.flight.record(
                    "lease_loss",
                    lanes=int(lost_lead.sum()),
                    first=np.nonzero(lost_lead)[0][:8].tolist())
            if lost_lead.any() and self._assigned:
                # waiters on lanes we no longer lead can never be
                # acked by us (the new leader may truncate them)
                for key in [k for k in self._assigned
                            if lost_lead[k[0]]]:
                    p = self._assigned.pop(key)
                    self.flight.record("tail", kind="failed_proposal",
                                       group=key[0], gindex=key[1],
                                       cause="leadership_lost",
                                       trace=p.trace)
                    self.w.trigger(p.id, None)
            if lost_lead.any() and self._ack_clock:
                # deposed lanes' in-flight stamps can never ack here
                self._ack_clock = {
                    k: v for k, v in self._ack_clock.items()
                    if not lost_lead[k[0]]}
            if lost_lead.any() and self._trace_live:
                self._trace_live = {
                    k: v for k, v in self._trace_live.items()
                    if not lost_lead[k[0]]}
            if lost_lead.any() and self._reads.pending:
                # reads pending on deposed lanes can never be
                # confirmed by us — fail them closed (the client
                # retries against the new leader; serving would be
                # the stale read this subsystem exists to prevent)
                for pr in self._reads.fail_lanes(lost_lead):
                    pr.ch.close(None)
            if won.any():
                # fresh-win grace for the check-quorum sweep: the
                # first acks take an RTT to arrive, and a basis of 0
                # must not read as "stale for ages"
                self._lead_since = np.where(won, now_m,
                                            self._lead_since)
                now_w = time.time()
                terms = mr.terms()
                for gi in np.nonzero(won)[0]:
                    self._elected_at[gi] = now_w
                    self._elected_term[gi] = terms[gi]
                    self._applied_at_elect[gi] = self.applied[gi]
                    self._first_apply_at[gi] = 0.0
            self._prev_lead = lead
            # /v2/stats/self role BEFORE any early return: followers
            # and freshly-deposed leaders must update too (the early
            # no-leader-lanes return below would otherwise freeze a
            # deposed host on StateLeader forever).  Leadership is
            # per-group; the scalar reference analog
            # (server.py soft_state) maps to leader-of-any.
            from ..raft.core import STATE_FOLLOWER, STATE_LEADER

            lead_any = bool(lead.any())
            hint = mr.leader_hint()
            self._hint_np = hint  # host cache for the read path
            known = hint[hint >= 0]
            self.server_stats.set_state(
                STATE_LEADER if lead_any else STATE_FOLLOWER,
                self.id if lead_any
                else (int(np.bincount(known).argmax())
                      if known.size else 0))
            n_new = np.zeros(self.g, np.int32)
            items: list[list[_Pending]] = [[] for _ in range(self.g)]
            for gi in range(self.g):
                q = self._requeue[gi]
                while q and len(items[gi]) < mr.e:
                    items[gi].append(q.popleft())
            for p in batch:
                gi = p.group if p.group is not None \
                    else group_of(p.req.path, self.g)
                if not lead[gi] or len(items[gi]) >= mr.e:
                    self._requeue[gi].append(p)
                    continue
                items[gi].append(p)
            for gi in range(self.g):
                n_new[gi] = len(items[gi])

            self._m_pending.set(
                sum(len(q) for q in self._requeue))
            new_keys: list[tuple[int, int]] = []
            recs: list[Entry] = []
            if n_new.any():
                with tracer.stage("dist.propose"), \
                        _ledger.dispatch("dist.propose"):
                    valid, base = mr.propose(
                        n_new, data=[[p.data for p in items[gi]]
                                     for gi in range(self.g)],
                        self_ack=False)
                for gi in range(self.g):
                    if not items[gi]:
                        continue
                    if not valid[gi]:
                        for p in items[gi]:
                            p.retries += 1
                            if p.retries < 50:
                                self._requeue[gi].append(p)
                            else:
                                self.flight.record(
                                    "tail", kind="failed_proposal",
                                    group=gi, cause="retry_exhausted",
                                    trace=p.trace)
                                self.w.trigger(p.id, None)
                        continue
                    for j, p in enumerate(items[gi]):
                        key = (gi, int(base[gi]) + 1 + j)
                        self._assigned[key] = p
                        new_keys.append(key)
                        if p.trace is not None:
                            # the traced proposal now has a log slot:
                            # frames carrying (gi, gindex) will ship
                            # its trace context to the followers
                            self._trace_live[key] = p.trace
                            self.flight.span(
                                p.trace, self.slot, "append",
                                group=gi, gindex=key[1])
                recs = self._entry_records(
                    [gi for gi in range(self.g)
                     if items[gi] and valid[gi]], base, items)
            elif not lead.any():
                return

            if new_keys:
                # ack-RTT clock starts NOW: entries are appended and
                # the frames leave next — this is the send edge of
                # the consensus round trip
                now_s = time.perf_counter()
                for key in new_keys:
                    self._ack_clock[key] = now_s

            # frames FIRST (the fsync/network overlap): the channel
            # writer threads ship them — and the followers append +
            # fsync — while our own WAL fsync below is still running
            with tracer.stage("dist.build_append"), \
                    _ledger.dispatch("dist.build_append"):
                self._pump_all()

            if recs:
                # entries (+ frontier) must be durable before OUR ack
                # counts; the overlap ledger row makes the saved wall
                # time readable off /metrics (dispatch_seconds =
                # fsync seconds that ran with frames in flight)
                try:
                    if self.pipe.inflight_total():
                        with tracer.stage("dist.persist"), \
                                _ledger.dispatch("dist.fsync_overlap"):
                            self._persist(recs)
                    else:
                        with tracer.stage("dist.persist"):
                            self._persist(recs)
                except EtcdNoSpace:
                    # full disk under a leader: the entries are in
                    # the engine log and their frames may already be
                    # in flight (fsync/network overlap) — HOLD the
                    # records for re-persist at recovery and do NOT
                    # self-ack (commit may still form from a quorum
                    # of FOLLOWER acks, which is legal Raft: the
                    # entry is durable elsewhere).  New writes are
                    # refused from here on.
                    self._enter_nospace("leader persist", held=recs)
                    recs = []
                if recs:
                    # fsync landed: NOW this host's copy joins the
                    # quorum
                    mr.ack_self(np.asarray(mr.state.last))
                    if self._trace_live and new_keys:
                        now_f = time.monotonic()
                        for key in new_keys:
                            tid = self._trace_live.get(key)
                            if tid is not None:
                                self.flight.span(tid, self.slot,
                                                 "leader_fsync",
                                                 t=now_f)
            else:
                # nothing appended here, but acks may have moved the
                # commit frontier since the last flush
                try:
                    self._persist([])
                except EtcdNoSpace:
                    # the frontier record is an optimization —
                    # losing it costs replay time, never acked data
                    self._enter_nospace("frontier persist")
            with tracer.stage("dist.apply"):
                self._apply_committed(self._assigned)
            # read maintenance: drop waiters whose callers timed out
            # (the age bound sits ABOVE the 30s get_many handler
            # budget so an in-budget caller is never force-failed
            # early), then sweep (applied/floor moved this round)
            now_r = time.monotonic()
            for pr in self._reads.expire(
                    now_r, max(35.0, 8.0 * self.post_timeout)):
                pr.ch.close(_EXPIRED)
            self._read_release(now_r)

    # -- the append pipeline (PR 5) ---------------------------------------

    def _channel(self, peer: int) -> PipeChannel:
        """The peer's pipelined append channel (lazily built; rebuilt
        when the peer's URL changed — a cached channel to the old
        address must not short-circuit the new route)."""
        url = self.peer_urls[peer]
        chan = self._channels.get(peer)
        if chan is not None and chan.url != url:
            chan.close()  # fails its in-flight: probe + resend
            chan = None
        if chan is None:
            chan = PipeChannel(
                url, "/mraft", stripes=self._n_stripes,
                timeout=self.post_timeout,
                ssl_context=self._peer_ssl_cli,
                on_resp=lambda seq, status, body, _p=peer:
                    self._on_pipe_resp(_p, seq, status, body),
                on_fail=lambda seqs, reason, _p=peer:
                    self._on_pipe_fail(_p, seqs, reason),
                on_sent=lambda seq, _p=peer:
                    self._on_pipe_sent(_p, seq),
                name=f"{self.slot}to{peer}",
                fault_ctx=(f"s{self.slot}", f"s{peer}"))
            self._channels[peer] = chan
        return chan

    def _set_inflight(self, peer: int) -> None:
        self._m_inflight[peer].set(self.pipe.inflight(peer))
        self._m_inflight_ents[peer].set(
            self.pipe.inflight_entries(peer))

    def _pump_all(self) -> None:
        for peer in range(self.m):
            if peer != self.slot:
                self._pump_peer(peer)

    def _pump_peer(self, peer: int) -> None:
        """Fill the peer's send window (call with self.lock held):
        data frames while the window has room and entries remain,
        plus ONE empty frame per heartbeat interval / commit advance
        (followers reset election timers and learn the commit vector
        from these).  ``next_`` advances optimistically at send, so
        consecutive frames carry consecutive windows without waiting
        for acks (etcd raft StateReplicate)."""
        mr = self.mr
        now = time.monotonic()
        # channel built only once there is something to send: spare
        # member slots (live < m) must not get idle socket threads
        chan = None
        commit = None
        # SNAPSHOT-mode evidence (PR 6): does ANY stripe's build see
        # a lane it could actually append to?  A peer whose every
        # active lane sits behind the compaction point gets the
        # window collapsed to one need-snap notification frame at
        # heartbeat cadence — a full window of append frames would
        # all be doomed while its streamed install runs.
        saw_active = saw_appendable = False
        for stripe in range(self._n_stripes):
            mask = self._stripe_masks[stripe]
            while self.pipe.can_send(peer):
                b = mr.build_append(peer, lane_mask=mask)
                if b is None:
                    # no led lanes in THIS stripe's mask — the other
                    # stripe may still lead lanes (e.g. leadership
                    # held on odd groups only), so fall through to
                    # it rather than returning
                    break
                n_ents = np.asarray(b.n_ents)
                has_ents = bool(n_ents.any())
                saw_active = True
                if bool((np.asarray(b.active)
                         & ~np.asarray(b.need_snap)).any()):
                    saw_appendable = True
                if (has_ents and self.pipe.inflight(peer)
                        and int(n_ents.sum()) < self._min_frame_ents):
                    # anti-fragmentation: a follower pays a full
                    # [G]-wide engine dispatch + fsync per FRAME
                    # regardless of entry count, so while the pipe is
                    # already busy, thin frames are pure overhead —
                    # hold the window until the frame is full enough
                    # (the in-flight ack re-pumps, so nothing
                    # starves; an idle pipe always sends immediately)
                    break
                if not has_ents:
                    # pure heartbeat / commit / need_snap frame:
                    # dedup on cadence and commit movement
                    if commit is None:
                        commit = np.asarray(b.commit)
                    adv = bool(((commit > self._sent_commit[peer])
                                & mask).any())
                    last = self.pipe.last_send(peer, stripe)
                    # a pending ReadIndex confirmation nudges ONE
                    # out-of-cadence heartbeat per stripe: its ack
                    # is the quorum round the queued reads piggyback
                    # on (last >= nudge time means this stripe
                    # already sent its post-registration frame)
                    due = (now - last >= self._hb_interval
                           or last < self._read_nudge_t)
                    if not (adv or due):
                        break
                meta = self.pipe.register(
                    peer, t0=now, nbytes=0, has_ents=has_ents,
                    stripe=stripe, n_ents=int(n_ents.sum()))
                b.seq, b.epoch = meta.seq, self.pipe.epoch
                mr.optimistic_advance(peer, b)
                if has_ents and self._trace_live:
                    # stamp the frame with every in-flight traced
                    # proposal it carries (the sampled subset only:
                    # _trace_live holds tens of keys, not the batch)
                    prev = np.asarray(b.prev_idx)
                    act = np.asarray(b.active) \
                        & ~np.asarray(b.need_snap)
                    tr = [(g_, gi_, tid, self.slot)
                          for (g_, gi_), tid
                          in self._trace_live.items()
                          if act[g_] and prev[g_] < gi_
                          <= prev[g_] + int(n_ents[g_])]
                    if tr:
                        b.trace = tr
                        meta.traced = True
                        self._traced_send[(peer, meta.seq)] = \
                            [[t[2], t[3]] for t in tr]
                with tracer.stage("dist.frame_marshal"):
                    payload = b.marshal()
                meta.nbytes = len(payload)
                self._m_frames.inc()
                self.server_stats.send_append()
                self._sent_commit[peer] = np.where(
                    mask, np.asarray(b.commit, np.int64),
                    self._sent_commit[peer])
                if chan is None:
                    chan = self._channel(peer)
                chan.send(meta.seq, payload, stripe)
                if not has_ents:
                    break
        if saw_active:
            if not saw_appendable:
                log.debug("dist[%d]: peer %d all lanes need-snap",
                          self.slot, peer)
                if self.pipe.note_snapshot(peer):
                    self.flight.record("pipe_mode", peer=peer,
                                       mode="snapshot")
            else:
                # the peer is past the compaction point on at least
                # one lane again (its install landed): leave
                # SNAPSHOT via one confirming probe frame
                if self.pipe.note_caught_up(peer):
                    self.flight.record("pipe_mode", peer=peer,
                                       mode="probe",
                                       cause="caught_up")
        self._set_inflight(peer)

    def _on_pipe_sent(self, peer: int, seq: int) -> None:
        """Channel writer callback: the frame's bytes just hit the
        socket.  Record the flight send event for traced frames —
        this is the accurate send edge of the stitcher's symmetric
        (send, recv, resp, ack) clock-alignment quads (stamping at
        register time would fold channel queue wait into the
        network hop).  dict.pop is GIL-atomic; no lock needed."""
        traces = self._traced_send.pop((peer, seq), None)
        if traces is not None:
            self.flight.record("frame", dir="send", peer=peer,
                               seq=seq, traces=traces)

    def _on_pipe_resp(self, peer: int, seq: int, status: int,
                      body: bytes) -> None:
        """Channel reader callback: one ack arrived."""
        if self.done.is_set():
            return
        # inbound half of the peerlink.recv failpoint: a dropped ack
        # simply evaporates — no progress, no failure signal — and
        # only the in-flight expire sweep recovers the window (the
        # asymmetric-partition case check-quorum step-down exists
        # for)
        try:
            act = _faults.hit("peerlink.recv",
                              src=self._peer_labels[peer],
                              dst=self._self_label)
        except OSError:
            act = _faults.DROP
        if act == _faults.DROP:
            return
        if status != 200:
            self._on_pipe_fail(peer, [seq], "reconnect")
            return
        try:
            resp = unmarshal_any(body)
        except Exception:
            self._on_pipe_fail(peer, [seq], "reconnect")
            return
        if not isinstance(resp, AppendResp):
            # a desynced/misbehaving peer answered with some other
            # frame kind: fail the seq like any bad response, or it
            # pins the window shut until the expire sweep
            self._on_pipe_fail(peer, [seq], "reconnect")
            return
        t1 = time.monotonic()
        with self.lock:
            if self.done.is_set():
                return
            self._absorb_ack(peer, resp, t1)

    def _on_pipe_fail(self, peer: int, seqs: list, reason: str) -> None:
        """Channel failure callback: these frames will never ack.
        Roll the peer back to probing from its confirmed match point
        — the optimistic next_ advances for the lost frames would
        otherwise leave a permanent hole until a reject round-trip
        repaired it."""
        if self.done.is_set():
            return
        for seq in seqs:
            # a never-sent (or never-acked) traced frame's send
            # registration must not leak in the stamp dict
            self._traced_send.pop((peer, seq), None)
        with self.lock:
            was = self.pipe.mode(peer)
            popped = self.pipe.fail(peer, seqs)
            if not popped:
                return
            mode = self.pipe.mode(peer)
            if mode != was:
                self.flight.record("pipe_mode", peer=peer, mode=mode,
                                   cause=reason)
            _obs.registry.counter("etcd_dist_frame_resend_total",
                                  reason=reason).inc(len(popped))
            self._m_send_fail.inc(len(popped))
            self.leader_stats.fail(self._member_id(peer))
            self.mr.probe_reset(peer)
            self._set_inflight(peer)

    def _absorb_ack(self, peer: int, resp: AppendResp,
                    t1: float) -> None:
        """Match + absorb one pipelined ack (call with lock held):
        monotone match/next update, quorum commit recomputed NOW (not
        at the next round), apply + client acks, then refill the
        peer's window."""
        mr = self.mr
        disp, meta = self.pipe.ack(peer, resp.seq, resp.epoch)
        if disp != "ok":
            _obs.registry.counter("etcd_dist_frame_resend_total",
                                  reason=disp).inc()
            higher = np.asarray(resp.term) > mr.terms()
            if higher.any():
                # an ack from a previous reign may still carry the
                # higher term that deposed us — the step-down must
                # not be lost, but its progress content (acked/ok/
                # hint) must not touch the OTHER lanes' state (those
                # indexes may have been truncated since; a full
                # active mask would reject-repair next_ on every
                # still-led lane).  Absorb a copy neutered to the
                # higher-term lanes only.
                mr.handle_append_resp(AppendResp(
                    sender=resp.sender, term=resp.term,
                    ok=np.zeros(self.g, bool), acked=resp.acked,
                    hint=resp.hint,
                    active=np.asarray(resp.active) & higher))
            return
        rtt = t1 - meta.t0
        self._m_send_rtt.observe(rtt)
        self.leader_stats.observe(self._member_id(peer), rtt)
        if meta.traced:
            # the ack edge of the clock-alignment quad (t1 was
            # stamped on the channel reader thread, pre-lock)
            self.flight.record("frame", t=t1, dir="ack", peer=peer,
                               seq=resp.seq)
            self._traced_send.pop((peer, resp.seq), None)
        with tracer.stage("dist.absorb"), \
                _ledger.dispatch("dist.absorb"):
            mr.handle_append_resp(resp)
        active = np.asarray(resp.active)
        ok = np.asarray(resp.ok)
        # lease / ReadIndex evidence (PR 7): count only active & OK
        # lanes — both are subsets of the follower's ``cur`` (it
        # held OUR term and reset its election timer when this frame
        # arrived).  ``active`` alone is NOT cur-only: the follower
        # folds need_snap lanes into it even at a HIGHER term so the
        # step-down can propagate (distmember.handle_append), and a
        # deposing ack must never extend a lease.  The cost is that
        # cur-but-rejected lanes (probe catch-up) don't renew —
        # conservative: the quorum's healthy members carry the basis.
        self.lease.note_ack(peer, meta.t0, active & ok)
        if (active & ~ok).any():
            # follower found a gap (dropped or out-of-order frame):
            # next_ was repaired from its commit hint; collapse to
            # PROBE so exactly one catch-up frame goes out
            if self.pipe.note_reject(peer):
                self.flight.record("pipe_mode", peer=peer,
                                   mode="probe", cause="reject")
            _obs.registry.counter("etcd_dist_frame_resend_total",
                                  reason="reject").inc()
        elif (active & ok).any():
            if self.pipe.note_ok(peer):
                self.flight.record("pipe_mode", peer=peer,
                                   mode="replicate")
        self._set_inflight(peer)
        with tracer.stage("dist.apply"):
            self._apply_committed(self._assigned)
        self._pump_peer(peer)
        # the ack may have advanced the quorum basis past pending
        # reads' registration times — the batched release sweep
        # rides the ack path, not a timer
        self._read_release()

    def _campaign(self, mask: np.ndarray) -> None:
        """Batched election round-trip for the fired lanes."""
        if self._nospace:
            # cannot durably record term/vote: campaigning (or
            # tallying a win whose becoming-leader entry can't
            # persist) is off the table until space returns
            return
        with self.lock:
            req = self.mr.begin_campaign(mask)
            try:
                self._persist_ballot()
            except EtcdNoSpace:
                # an un-durable self-vote must not leave the host
                self._enter_nospace("campaign ballot")
                return
            payload = req.marshal()
            self._m_campaigns.inc(
                int(np.asarray(req.active).sum()))
        votes = [v for v in self._exchange(
            [(p, payload) for p in range(self.m) if p != self.slot])
            if isinstance(v, VoteResp)]
        if self.done.is_set():
            return  # stopping: don't tally/persist past stop()
        with self.lock:
            won = self.mr.tally(req.active, votes)
            self._m_wins.inc(int(won.sum()))
            # election forensics in the black box: which lanes
            # campaigned, how many answered, how many lanes won, at
            # what term — the always-on record the drill's post-
            # mortem used to grep stdout for
            fired = np.asarray(req.active)
            self.flight.record(
                "election", fired=int(fired.sum()),
                won=int(won.sum()), resps=len(votes),
                term=int(np.asarray(self.mr.state.term).max()),
                lanes=np.nonzero(fired)[0][:8].tolist())
            try:
                self._persist_ballot()
            except EtcdNoSpace:
                self._enter_nospace("tally ballot")
                return
            lost = int(np.asarray(req.active).sum()) \
                - int(won.sum())
            if lost and self._debug_elections:
                # liveness forensics (chaos drill): which lanes
                # campaigned, how many peers answered, what they said
                log.info(
                    "dist[%d]: campaign lost %d lanes (fired=%s, "
                    "resps=%d, grants=%s, terms=%s)", self.slot,
                    lost, np.nonzero(np.asarray(req.active))[0][:8],
                    len(votes),
                    [np.asarray(v.granted).astype(int)[:8].tolist()
                     for v in votes],
                    np.asarray(self.mr.state.term)[:8])
            if won.any():
                log.info("dist[%d]: won %d groups", self.slot,
                         int(won.sum()))
                # becoming-leader empty entry (raft.go:329-348) —
                # replicated and committed via the normal rounds
                valid, base = self.mr.propose(
                    won.astype(np.int32),
                    data=[[b""] if won[gi] else []
                          for gi in range(self.g)])
                recs = []
                terms = self.mr.terms()
                for gi in np.nonzero(valid)[0]:
                    self.seq += 1
                    recs.append(Entry(
                        index=self.seq, term=self.raft_term,
                        data=GroupEntry(
                            kind=K_ENTRY, group=int(gi),
                            gindex=int(base[gi]) + 1,
                            gterm=int(terms[gi])).marshal()))
                try:
                    self._persist(recs)
                except EtcdNoSpace:
                    # the becoming-leader entries live in the engine
                    # log with frames about to pump: hold their
                    # records for recovery, same as the leader-round
                    # persist
                    self._enter_nospace("campaign persist",
                                        held=recs)

    def _exchange(self, frames: list[tuple[int, bytes]],
                  track: bool = False) -> list:
        """POST one frame per peer concurrently; returns the parsed
        responses that arrived (drops parse failures and dead peers).
        With ``track`` (the APPEND round only — vote traffic must not
        skew follower stats, matching the reference's MSG_APP-only
        tracking, sender.py), per-peer round-trip latency feeds
        /v2/stats/leader keyed by member id."""
        if not frames:
            return []
        if self.done.is_set():
            return []  # stop() may have shut the pool down already

        def one(arg):
            peer, payload = arg
            self._m_frames.inc()
            t0 = time.perf_counter()
            out = self._post_peer(peer, "/mraft", payload)
            if out is None:
                self._m_send_fail.inc()
                if track:
                    self.leader_stats.fail(self._member_id(peer))
                return None
            rtt = time.perf_counter() - t0
            self._m_send_rtt.observe(rtt)
            try:
                parsed = unmarshal_any(out)
            except Exception:
                if track:
                    self.leader_stats.fail(self._member_id(peer))
                return None
            if track:
                self.leader_stats.observe(
                    self._member_id(peer), rtt)
            return parsed

        try:
            return [r for r in self._xchg_pool.map(one, frames)
                    if r is not None]
        except RuntimeError:
            # stop() shut the pool between the done-check and map()
            if self.done.is_set():
                return []
            raise

    def _member_id(self, slot: int) -> int:
        """Stats key for peer ``slot``: its registered member id when
        the replicated registry has it (peers publish name->id with
        their peer URL), else the slot index as a placeholder until
        the registration commits."""
        cached = self._slot_ids.get(slot)
        if cached is not None:
            return cached
        try:
            url = self.peer_urls[slot]
            for m in self.cluster_store.get().values():
                if url in m.peer_urls:
                    self._slot_ids[slot] = m.id
                    return m.id
        except Exception:
            pass
        return slot

    def _post_peer(self, peer: int, path: str,
                   payload) -> bytes | None:
        """Synchronous POST over the shared keep-alive cache
        (peerlink.KeepAlivePool — the same abstraction behind the
        classic sender; at-least-once delivery contract and the
        URL-change/stale-socket handling live there).  Used by the
        vote round-trips; append frames ride the pipelined channels
        instead.  Both directions cross the peerlink failpoints
        (PR 10): a dropped send or a dropped response is a dropped
        message — by contract, recovered by retry."""
        try:
            if _faults.hit("peerlink.send", src=self._self_label,
                           dst=self._peer_labels[peer]) \
                    == _faults.DROP:
                return None
        except OSError:
            return None
        out = self._pool.post(peer, self.peer_urls[peer], path,
                              payload)
        if out is None or out[0] != 200:
            return None
        try:
            if _faults.hit("peerlink.recv",
                           src=self._peer_labels[peer],
                           dst=self._self_label) == _faults.DROP:
                return None
        except OSError:
            return None
        return out[1]

    # -- apply ------------------------------------------------------------

    def _apply_committed(self, assigned=None) -> None:
        """Apply newly committed entries to the local replica (call
        with lock held); leader lanes also ack their waiters."""
        mr = self.mr
        commit = mr.commit_index().astype(np.int64)
        newly = commit > self.applied
        if not newly.any():
            return
        t_apply = time.perf_counter()
        n_apply = int((commit - self.applied)[newly].sum())
        # batch the whole commit window into ONE fanout dispatch; the
        # round scope keeps watcher matching/delivery off this path
        # (we hold self.lock here — the engine thread picks it up)
        sink = self.commit_sink
        sink_rows: list | None = [] if sink is not None else None
        with self.store.fanout_round():
            self._apply_window(assigned, mr, commit, newly,
                               sink_rows)
        if sink_rows:
            # the ring write is a bounded memcpy that never blocks
            # (shmring drops + counts on overrun), so it can ride
            # the apply path without threatening raft liveness
            with tracer.stage("role.handoff_marshal"):
                sink.push(sink_rows)
        self._m_apply_n.observe(n_apply)
        self._m_apply_s.observe(time.perf_counter() - t_apply)
        mr.mark_applied(self.applied)
        # follower linearizable reads park on commit-index
        # wait-points; the advanced apply frontier releases them
        if self._waits.pending:
            for ch in self._waits.release(self.applied):
                ch.close(True)
        # lane-fill compaction, decoupled from the snap_count-gated
        # snapshot: periodic SYNC entries alone would fill a group's
        # fixed-cap log window on an idle cluster long before 10k
        # applies accumulate, wedging that lane permanently
        st = mr.state
        fill = np.asarray(st.last) - np.asarray(st.offset)
        if (fill > (mr.cap * 3) // 4).any():
            mr.compact()
        if self.raft_index - self._snapi > self.snap_count:
            # deferred to the round loop: _apply_committed runs
            # under self.lock (round loop AND ack/handler threads),
            # and snapshot()'s disk I/O must not run there
            self._want_snap = True

    def _apply_window(self, assigned, mr, commit, newly,
                      sink_rows: list | None = None) -> None:
        """Per-group apply loop (split from _apply_committed so the
        fanout round brackets exactly the store mutations)."""
        for gi in np.nonzero(newly)[0]:
            for idx in range(int(self.applied[gi]) + 1,
                             int(commit[gi]) + 1):
                # quorum-acked and applying: close the ack-RTT clock
                key = (int(gi), idx)
                ts = self._ack_clock.pop(key, None)
                rtt = None
                if ts is not None:
                    rtt = time.perf_counter() - ts
                    self._m_ack.observe(rtt)
                tid = self._trace_live.pop(key, None) \
                    if self._trace_live else None
                if tid is not None:
                    self.flight.span(tid, self.slot, "commit",
                                     group=key[0], gindex=key[1])
                if rtt is not None and rtt > self.flight.slow_s:
                    # TAIL capture: a slow proposal lands in the ring
                    # even when head sampling missed it — the ring
                    # always holds the outliers
                    self.flight.record("tail", kind="slow_proposal",
                                       group=key[0], gindex=key[1],
                                       rtt_ms=round(rtt * 1e3, 2),
                                       trace=tid)
                payload = mr.committed_payload(int(gi), idx)
                resp = None
                if payload:
                    # leader fast path: the waiter's _Pending still
                    # holds the parsed Request — skip re-unmarshaling
                    # the payload it was built from
                    pend = (assigned or {}).get((int(gi), idx))
                    r = (pend.req if pend is not None
                         else Request.unmarshal(payload))
                    if r.method == "CONFCHANGE":
                        # committed membership change for THIS group
                        # (server.go:542-559): every host applies it
                        # at its own apply frontier
                        self._apply_conf_change(int(gi), r)
                        resp = Response()
                    else:
                        resp = apply_request_to_store(self.store, r)
                        if sink_rows is not None:
                            sink_rows.append((int(gi), idx, payload))
                self.raft_index += 1
                if tid is not None:
                    self.flight.span(tid, self.slot, "apply")
                p = (assigned or {}).pop((int(gi), idx), None)
                if p is not None:
                    self.w.trigger(p.id, resp)
                elif payload:
                    self.w.trigger(r.id, resp)
                if tid is not None:
                    self.flight.span(tid, self.slot, "client_ack")
            self.applied[gi] = commit[gi]
            if (self._first_apply_at[gi] == 0.0
                    and self._elected_at[gi] > 0.0
                    and self.applied[gi] > self._applied_at_elect[gi]):
                self._first_apply_at[gi] = time.time()

    # -- NOSPACE read-only mode (PR 10) -----------------------------------

    def _enter_nospace(self, cause: str,
                       held: list[Entry] | None = None) -> None:
        """Flip into read-only mode (call with self.lock held).
        ``held`` carries leader-side WAL records whose entries are
        already in the engine log — they re-persist FIRST at
        recovery, before this host's durable self-ack counts."""
        if held:
            self._held_recs = (self._held_recs or []) + held
        if self._nospace:
            return
        self._nospace = True
        self._nospace_backoff.reset()
        self._nospace_probe_t = (time.monotonic()
                                 + self._nospace_backoff.next())
        self._m_nospace.set(1)
        self.flight.record("nospace", state="enter", cause=cause)
        log.error("dist[%d]: ENTERING NOSPACE read-only mode (%s): "
                  "writes rejected with errorCode 405, reads keep "
                  "serving, disk probed with backoff", self.slot,
                  cause)

    def _exit_nospace(self) -> None:
        """Leave read-only mode (call with self.lock held)."""
        if not self._nospace:
            return
        self._nospace = False
        self._nospace_backoff.reset()
        self._m_nospace.set(0)
        # force the next _persist to write a fresh frontier record
        # (frontier saves were skipped throughout the episode)
        self._fr_last = None
        self.flight.record("nospace", state="exit")
        log.warning("dist[%d]: NOSPACE recovered — accepting writes "
                    "again", self.slot)

    def _nospace_recover(self) -> None:
        """Round-loop recovery probe: exercise the WAL's append +
        fsync seams; on success re-persist any held leader records
        (their entries were never self-acked) and re-open for
        writes.  Failure re-arms the probe with the shared
        backoff — a full disk is polled, never crash-looped."""
        try:
            with self.lock:
                self.wal.probe_space()
                if self._held_recs:
                    self._persist(self._held_recs)
                    self._held_recs = None
                    self.mr.ack_self(np.asarray(self.mr.state.last))
                self._exit_nospace()
        except EtcdNoSpace:
            delay = self._nospace_backoff.next()
            with self.lock:
                self._nospace_probe_t = time.monotonic() + delay

    # -- snapshot / catch-up ----------------------------------------------

    def snapshot(self) -> None:
        """Durable snapshot → engine compaction → WAL cut → segment
        GC (PR 6).  Crash-ordering: save_snap fsyncs the snapshot
        file AND its directory entry before returning (the PR 1
        invariant), so by the time gc() unlinks segments the
        superseding artifact is durable — a crash anywhere in this
        sequence restarts either from the old chain (snapshot saved,
        nothing deleted yet) or from a seq-contiguous suffix still
        covering the GC boundary (gc removes oldest-first with a
        dir fsync per unlink).  The boundary is the OLDEST retained
        snapshot's index, not the newest: load() must be able to
        fall back across the whole retention window and replay
        forward from whichever snapshot survives.

        Lock discipline: only the state capture and the WAL/engine
        mutations hold ``self.lock`` — the snapshot file's
        write+fsync+purge (the seconds-long part on a big store)
        runs OUTSIDE it, so peer frames and client ops don't stall
        behind snapshot disk I/O; ``_snap_mutex`` serializes
        concurrent snapshot() calls instead."""
        try:
            with self._snap_mutex:
                with self.lock:
                    snap_seq = self.seq
                    # only the tree->dict capture (store.save) needs
                    # the lock; the outer dumps re-escapes the whole
                    # embedded store string — comparable cost again —
                    # and must not stall handlers/round loop for it
                    d = self._snapshot_dict()
                    term = self.raft_term
                blob = json.dumps(d).encode()
                with tracer.stage("dist.snapshot"):
                    # only this process's snapshot() writes the snap
                    # dir, and _snap_mutex is held: safe outside
                    # self.lock
                    self.ss.save_snap(Snapshot(
                        data=blob, index=snap_seq, term=term))
                    with self.lock:
                        self.mr.compact()
                        if log.isEnabledFor(logging.DEBUG):
                            log.debug(
                                "dist[%d]: post-compact offset=%s "
                                "applied=%s lead=%s", self.slot,
                                np.asarray(
                                    self.mr.state.offset).tolist(),
                                np.asarray(
                                    self.mr.state.applied).tolist(),
                                np.asarray(self.mr.is_leader())
                                .astype(int).tolist())
                        self.wal.cut()
                        floor = self.ss.retained_floor()
                        self.wal.gc(snap_seq if floor is None
                                    else floor)
                self._snapi = self.raft_index
        except EtcdNoSpace as e:
            # snapshot save / WAL cut hit a full disk: the one state
            # GC could have shrunk keeps growing, so degrade to
            # read-only instead of crash-looping the snapshot thread
            with self.lock:
                self._enter_nospace(f"snapshot: {e.cause}")
            return
        log.info("dist[%d]: snapshot at seq=%d", self.slot, snap_seq)

    def _snapshot_bg(self) -> None:
        """Thread body for the round-loop-deferred snapshot: never
        let a snapshot failure kill the thread loudly mid-shutdown
        (stop() closes the WAL after joining us, but a crashed donor
        disk etc. must surface as a log line, not a lost thread)."""
        try:
            self.snapshot()
        except Exception:
            if not self.done.is_set():
                log.exception("dist[%d]: deferred snapshot failed",
                              self.slot)

    def _install_ctr(self, outcome: str):
        # the one copy of the outcome-counter lookup lives with the
        # stream module; every outcome fetched here is inc'd at the
        # call site, so recording the flight event at fetch keeps
        # install outcomes in the black box without touching each of
        # the eight call sites.  chunk_reject is billed INSIDE the
        # puller (snap/stream.py) and reaches the ring through the
        # on_reject hook _stream_snapshot wires up.
        from ..snap.stream import _install_ctr

        self.flight.record("snap_install", outcome=outcome)
        return _install_ctr(outcome)

    def _pull_snapshot_bg(self) -> None:
        """Thread body for the round-loop-deferred pull: any
        unexpected failure (a donor bug the typed guards missed)
        must re-arm with backoff and log — a raise here would kill
        the thread silently and drop the pull request."""
        try:
            self._pull_snapshot()
        except Exception:
            if not self.done.is_set():
                log.exception("dist[%d]: snapshot pull failed",
                              self.slot)
                self._arm_pull_retry()

    def _arm_pull_retry(self) -> None:
        """Re-arm the pull with jittered exponential backoff: the
        need is NOT dropped on an all-donors-failed attempt (the
        pre-PR-6 wedge — a lagging peer sat stuck until an
        unrelated need_snap frame happened to re-trigger it)."""
        with self.lock:
            self._need_pull = True
            delay = self._pull_backoff.next()
            self._pull_not_before = time.monotonic() + delay
        log.info("dist[%d]: snapshot pull failed on every donor; "
                 "retrying in %.2fs", self.slot, delay)

    def _fetch_snap_meta(self, h: int) -> dict | None:
        """Meta pin fetch.  NOT on the shared keep-alive pool: the
        donor serializes + CRC-chains its whole store before
        replying, which on a big snapshot takes far longer than the
        pool's post_timeout read deadline — a short meta timeout
        would make large-snapshot pulls (the very case the stream
        exists for) unable to get past step one."""
        req = urllib.request.Request(
            self.peer_urls[h] + SNAP_META_PATH, data=b"",
            method="POST",
            headers={"Content-Type": "application/octet-stream"})
        # scale the wait with the donor's probed store size (1 MiB/s
        # serialization floor on top of the fixed slack): a fixed
        # timeout turns every donor of a big-enough store into
        # "unreachable" at step one — all donors fail identically and
        # the peer can never catch up, the wedge class this path
        # exists to fix
        hint_s = self._donor_size_hint.get(h, 0) / (1 << 20)
        try:
            with urllib.request.urlopen(
                    req,
                    timeout=max(30.0, 10 * self.post_timeout) + hint_s,
                    context=self._peer_ssl_cli) as resp:
                body = resp.read()
        except (urllib.error.URLError, OSError):
            return None  # unreachable donor
        try:
            return json.loads(body.decode())
        except ValueError:
            # the donor ANSWERED but with unparseable meta: a real
            # failed attempt (donor-side bug), distinct from an
            # unreachable donor — the documented meta_failed outcome
            self._install_ctr("meta_failed").inc()
            return None

    def _fetch_snap_frontier(self, h: int) -> np.ndarray | None:
        """Cheap pre-pin dominance probe (GET, no pin, no store
        serialization on the donor)."""
        try:
            with urllib.request.urlopen(
                    self.peer_urls[h] + SNAP_FRONTIER_PATH,
                    timeout=max(2.0, self.post_timeout),
                    context=self._peer_ssl_cli) as resp:
                d = json.loads(resp.read().decode())
            # remember the donor's size hint for the meta-fetch
            # timeout (absent on peers without a durable snapshot)
            self._donor_size_hint[h] = int(d.get("approx_bytes", 0))
            return np.asarray(d["frontier"], np.int64)
        except (urllib.error.URLError, OSError, ValueError,
                KeyError, TypeError):
            return None

    def _stream_snapshot(self, h: int, meta: dict) -> bytes:
        """Pull one pinned snapshot stream from donor ``h`` (chunked
        over a peerlink channel, rolling-CRC verified, resume from
        the last verified chunk on reconnect).  Raises
        SnapStreamError/StaleSourceError."""
        # the overall deadline must scale with the snapshot size: a
        # fixed cap aborts every attempt on a big-snapshot/slow-link
        # pull that is making steady progress (each retry starts over
        # against a NEW pin, so the peer would never catch up — the
        # exact wedge this path exists to fix).  120s of slack plus a
        # 1 MiB/s average-throughput floor; genuine no-progress is
        # the stall detector's job, not the deadline's.
        deadline = 120.0 + int(meta.get("size", 0)) / (1 << 20)
        puller = ChunkPuller(
            self.peer_urls[h], meta,
            ssl_context=self._peer_ssl_cli,
            timeout=self.post_timeout,
            window=4, deadline_s=deadline,
            abort=self.done.is_set,
            on_reject=lambda k: self.flight.record(
                "snap_install", outcome="chunk_reject", chunk=k,
                donor=h),
            name=f"snap{self.slot}from{h}")
        try:
            return puller.run()
        finally:
            puller.close()

    def _pull_snapshot(self) -> None:
        """Streamed snapshot install (PR 6; msgSnap-as-pull).

        Donors are tried in leader-hint order (then the remaining
        peers): meta pin → dominance check → chunked stream →
        install.  Installs only when the snapshot's frontier
        dominates our applied vector — the store blob is the merged
        state of ALL groups, so a partial install could regress
        groups that are ahead; a uniformly-behind (fresh or
        restarted) member always qualifies, which is the case the
        pull path exists for.  A TRANSPORT-class failure (donor
        unreachable, meta unreadable, stream aborted) re-arms
        ``_need_pull`` with backoff instead of dropping it (the
        pre-PR-6 wedge); a SNAPSHOT-class miss (not dominating,
        rejected by every lane) does NOT re-arm — it means appends
        are already flowing on lanes ahead of the pin, and the next
        genuine need_snap frame re-sets the flag if a lane is still
        behind the compaction point (an unconditional re-arm here
        turns the benign already-caught-up case into an infinite
        pull loop — found by the deep-lag drill)."""
        lead = self.mr.leader_hint()
        hinted = sorted({int(s) for s in lead
                         if s >= 0 and s != self.slot})
        rest = [p for p in range(self.m)
                if p != self.slot and p not in hinted]
        donors = hinted + rest
        tried = 0
        transport_failed = False
        for h in donors:
            if self.done.is_set():
                return
            # cheap dominance pre-probe BEFORE the meta pin: a pin
            # makes the donor serialize + CRC-chain its whole store
            # under its lock and hold the blob for the cache TTL —
            # a spurious _need_pull on a caught-up peer must not
            # cost every donor that (the probe is one small GET).
            # Dominance is re-checked post-pin and again under the
            # lock at install; this is only the cheap early exit.
            probe = self._fetch_snap_frontier(h)
            if probe is None:
                continue  # unreachable donor: not an attempt
            with self.lock:
                probe_dominates = bool((probe >= self.applied).all())
            if not probe_dominates:
                log.info("dist[%d]: donor %d frontier probe does "
                         "not dominate; skipping without pin",
                         self.slot, h)
                self._install_ctr("not_dominating").inc()
                tried += 1
                continue
            meta = self._fetch_snap_meta(h)
            if meta is None:
                continue  # unreachable donor: not an attempt
            tried += 1
            # one stale-pin retry per donor: the pin may have aged
            # out (or the donor restarted) between meta and chunks
            for attempt in range(2):
                try:
                    frontier = np.asarray(meta["frontier"], np.int64)
                    terms = np.asarray(meta["terms"], np.int64)
                    members = (np.asarray(meta["members"], bool)
                               if "members" in meta else None)
                    if frontier.shape != self.applied.shape:
                        raise ValueError("frontier shape mismatch")
                except (KeyError, TypeError, ValueError):
                    # parseable JSON but not a stream header (donor
                    # bug / version skew): the documented meta_failed
                    # outcome — a bare KeyError here would kill the
                    # pull thread instead of counting + backing off
                    self._install_ctr("meta_failed").inc()
                    transport_failed = True
                    break
                with self.lock:
                    dominates = bool((frontier >= self.applied).all())
                if not dominates:
                    log.info("dist[%d]: snapshot from %d does not "
                             "dominate; skipping", self.slot, h)
                    self._install_ctr("not_dominating").inc()
                    break
                try:
                    payload = self._stream_snapshot(h, meta)
                except StaleSourceError:
                    meta = self._fetch_snap_meta(h)
                    if meta is None or attempt == 1:
                        self._install_ctr("stream_failed").inc()
                        transport_failed = True
                        break
                    continue
                except SnapStreamError as e:
                    log.warning("dist[%d]: snapshot stream from %d "
                                "failed: %s", self.slot, h, e)
                    self._install_ctr("stream_failed").inc()
                    transport_failed = True
                    break
                try:
                    blob = json.loads(payload.decode())
                except ValueError:
                    # verified chunks but an unparseable payload:
                    # donor-side serialization bug, not transport
                    self._install_ctr("stream_failed").inc()
                    break
                with self.lock:
                    # dominance re-checked under the lock: appends
                    # absorbed during the (unlocked) stream may have
                    # advanced us past this snapshot
                    if not (frontier >= self.applied).all():
                        self._install_ctr("stale").inc()
                        break
                    inst = self.mr.install_snapshot(
                        frontier, terms, members=members)
                    if not inst.any():
                        self._install_ctr("stale").inc()
                        break
                    self.store.recovery(blob["store"].encode())
                    self.applied = frontier.copy()
                    self.raft_index = blob.get("applied_total",
                                               self.raft_index)
                    self.raft_term = max(self.raft_term,
                                         int(terms.max()))
                    try:
                        self._persist([])
                    except EtcdNoSpace:
                        # the install is in-memory state; a member
                        # that restarts before space returns simply
                        # re-pulls (need_snap re-fires)
                        self._enter_nospace("install persist")
                    # the installed frontier may cover parked
                    # follower reads, and the snapshot's membership
                    # feeds the read path's quorum math
                    self._refresh_member_cache()
                    if self._waits.pending:
                        for ch in self._waits.release(self.applied):
                            ch.close(True)
                    self._pull_backoff.reset()
                    self._pull_not_before = 0.0
                    log.info("dist[%d]: installed streamed snapshot "
                             "from host %d (%d lanes, %d bytes)",
                             self.slot, h, int(inst.sum()),
                             len(payload))
                self._install_ctr("ok").inc()
                return
        if tried == 0:
            self._install_ctr("no_donor").inc()
        if tried == 0 or transport_failed:
            self._arm_pull_retry()

    # -- runtime membership (server.go:382-404, 542-559, per host) --------

    def add_member(self, slot: int,
                   timeout: float | None = 30.0) -> None:
        """Grow every group to include the host at member ``slot``
        (its URL must already be in peer_urls — slots are pre-sized;
        start the cluster with spare slots via ``live``).  One
        ConfChange per group, committed under the OLD quorum."""
        self._conf_change(True, slot, timeout)

    def remove_member(self, slot: int,
                      timeout: float | None = 30.0) -> None:
        self._conf_change(False, slot, timeout)

    def _conf_change(self, add: bool, slot: int,
                     timeout: float | None) -> None:
        """Each group's ConfChange goes through do() — which forwards
        to THAT group's leader host like any write (leadership is
        per-group and commonly split across hosts, so a local-queue-
        only submission would commit on this host's lanes and drop
        the rest, diverging per-group membership).  Groups run
        concurrently; any failure raises after the sweep."""
        if not (0 <= slot < self.m):
            raise ValueError(
                f"slot {slot} out of range ({self.m} member slots "
                f"= len(peer_urls); start with spare URLs to grow)")
        from concurrent.futures import ThreadPoolExecutor

        payload = json.dumps({"add": bool(add), "slot": int(slot)})

        def one(gi: int):
            self.do(Request(method="CONFCHANGE", id=gen_id(),
                            path=f"/_confchange/{gi}", val=payload),
                    timeout=timeout)

        with ThreadPoolExecutor(min(self.g, 16)) as pool:
            futs = {gi: pool.submit(one, gi) for gi in range(self.g)}
            failed = [gi for gi, f in futs.items()
                      if f.exception() is not None]
        if failed:
            raise TimeoutError(
                f"conf change uncommitted on {len(failed)} group(s) "
                f"(e.g. {failed[:4]}): "
                f"{futs[failed[0]].exception()}")

    def _apply_conf_change(self, gi: int, r: Request) -> None:
        d = json.loads(r.val)
        mask = np.zeros(self.g, bool)
        mask[gi] = True
        self.mr.apply_conf_change(bool(d["add"]), int(d["slot"]),
                                  mask=mask)
        # the read path's quorum-basis math keys off membership
        self._refresh_member_cache()

    def members_of(self, gi: int) -> np.ndarray:
        """[M] live-membership mask of group ``gi``."""
        return np.asarray(self.mr.state.members)[gi]

    # -- RaftTimer --------------------------------------------------------

    def index(self) -> int:
        return self.raft_index

    def term(self) -> int:
        return self.raft_term


# -- peer HTTP plumbing -----------------------------------------------------


class _PeerHTTPServer(ThreadingHTTPServer):
    """Peer/batch listener.  The stdlib default listen backlog of 5
    drops SYNs (= connection resets) the moment a read-heavy client
    pool opens its connections together — the PR 7 get_many lane
    serves dozens of concurrent client connections, not just the
    two peer hosts.  Backlog is centralized in the front door
    (PR 12) so the peer/client asymmetry cannot reappear."""

    request_queue_size = LISTEN_BACKLOG


def pack_requests(reqs: list[Request]) -> bytes:
    """Batch-propose body: u32 count, then u32 length + marshaled
    Request per item (the /mraft/propose_many frame; shared by the
    server parser and bench/client writers)."""
    import struct

    parts = [struct.pack("<I", len(reqs))]
    for r in reqs:
        b = r.marshal()
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    return b"".join(parts)


def unpack_requests(body: bytes) -> list[Request]:
    import struct

    if len(body) < 4:
        raise ValueError("short batch frame")
    (n,) = struct.unpack_from("<I", body, 0)
    pos, out = 4, []
    for _ in range(n):
        if pos + 4 > len(body):
            raise ValueError("truncated batch frame")
        (ln,) = struct.unpack_from("<I", body, pos)
        pos += 4
        if pos + ln > len(body):
            raise ValueError("truncated batch item")
        out.append(Request.unmarshal(body[pos:pos + ln]))
        pos += ln
    return out


def _refwd_not_leader(server: "DistServer", reqs: list[Request],
                      res: list, timeout: float = 30.0) -> list:
    """do_many answers follower-received writes with
    ``TimeoutError("not leader")`` — the batch lane never
    re-forwards (its clients target leaders).  The role-split ingest
    always posts to its LOCAL shard, so on follower hosts every
    write would bounce; re-drive just the misses through the
    single-op path, which forwards to the group leader.  The extra
    hop is only paid on non-leader hosts for non-leader groups."""
    out = list(res)
    for i, x in enumerate(out):
        if isinstance(x, TimeoutError) and "not leader" in str(x):
            try:
                out[i] = server.do(reqs[i], timeout=timeout)
            except Exception as e:
                out[i] = e
    return out


def _make_peer_handler(server: DistServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # per-connection socket timeout: bounds the deferred TLS
        # handshake and any stalled peer read in the worker thread
        timeout = 30

        def log_message(self, *a):  # quiet
            pass

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(n)

        def do_POST(self):
            try:
                if self.path == "/mraft/faults":
                    # runtime fault control (PR 10): the nemesis
                    # drill arms and clears failpoint specs mid-run.
                    # Routed BEFORE the http.peer failpoint below —
                    # an armed http.peer drop must never lock out
                    # its own clear path.  Body: {"spec": "...",
                    # "seed": N}; empty spec clears.  A bad spec is
                    # a loud 400 — a typo'd failpoint must never
                    # silently inject nothing.
                    try:
                        d = json.loads(self._body() or b"{}")
                        _faults.FAULTS.configure(
                            d.get("spec", ""), seed=d.get("seed"))
                        self._reply(200, json.dumps(
                            {"ok": True,
                             "spec": _faults.FAULTS.spec}).encode())
                    except (_faults.FaultSpecError, ValueError,
                            TypeError) as e:
                        self._reply(400, json.dumps(
                            {"ok": False,
                             "message": str(e)}).encode())
                    return
                # http.peer failpoint: whole-surface delay / error /
                # connection drop for the peer tier
                try:
                    if _faults.hit("http.peer") == _faults.DROP:
                        self.close_connection = True
                        return
                except OSError:
                    self._reply(503, b"")
                    return
                if self.path == "/mraft":
                    try:
                        out = server.handle_frame(self._body())
                    except ServerStoppedError:
                        self._reply(503, b"")
                        return
                    except EtcdNoSpace:
                        # read-only member: a distinct status the
                        # sender reads as "frame refused" (teardown
                        # + probe), distinct from the stopping 503
                        # in the logs
                        self._reply(507, b"")
                        return
                    except FrameDropped:
                        # injected inbound loss: no response at all —
                        # the sender sees a dead connection, exactly
                        # like a lost frame
                        self.close_connection = True
                        return
                    self._reply(200, out)
                elif self.path == SNAP_META_PATH:
                    # pin a fresh snapshot serialization; the reply
                    # is the stream header (id + chunk CRC chain)
                    self._body()
                    self._reply(200, server.snapshot_stream_meta())
                elif self.path == SNAP_CHUNK_PATH:
                    code, data = server.snapshot_stream_chunk(
                        self._body())
                    self._reply(code, data)
                elif self.path == "/mraft/propose":
                    try:
                        resp = server.handle_forward(
                            self._body(), timeout=5.0)
                        ev = resp.event.to_dict() \
                            if resp.event is not None else None
                        self._reply(200, json.dumps(
                            {"ok": True, "event": ev}).encode())
                    except Exception as e:
                        code = getattr(e, "error_code", 300)
                        self._reply(200, json.dumps(
                            {"ok": False, "errorCode": code,
                             "message": str(e)}).encode())
                elif self.path == "/mraft/propose_many":
                    # pipelined batch (do_many): one connection keeps
                    # a whole window of writes in flight.  The reply
                    # is error-sparse — {"n": N, "errs": {idx: ...}}
                    # — because at window 512 a per-request verdict
                    # list made the leader encode (and every client
                    # decode) ~12 KB of JSON per batch on the serving
                    # core; the common all-ok batch is now ~20 bytes.
                    # A client that advertised the binary framing
                    # (Accept, PR 14) gets the fixed-width DCB1 form
                    # instead — 16 bytes all-ok, no JSON encode.
                    try:
                        # the propose BODY is the version-stable
                        # packed Request batch on every wire (a
                        # downgrade must never re-send a write), so
                        # its parse is ingest cost, not client-wire
                        # cost — attributed apart from the
                        # Accept-negotiated client.* stages
                        with tracer.stage("dist.parse_batch"):
                            reqs = unpack_requests(self._body())
                        res = server.do_many(reqs, timeout=30.0)
                        if self._binary_ok():
                            with tracer.stage("client.marshal"):
                                body = bytes(
                                    clientmsg.pack_propose_response(
                                        len(res),
                                        {i: (getattr(x, "error_code",
                                                     300), str(x))
                                         for i, x in enumerate(res)
                                         if not isinstance(
                                             x, Response)}))
                            self._reply(200, body,
                                        ctype=clientmsg.CONTENT_TYPE)
                            return
                        with tracer.stage("client.marshal"):
                            errs = {}
                            for i, x in enumerate(res):
                                if not isinstance(x, Response):
                                    errs[str(i)] = {
                                        "errorCode": getattr(
                                            x, "error_code", 300),
                                        "message": str(x)}
                            body = json.dumps(
                                {"n": len(res),
                                 "errs": errs}).encode()
                        self._reply(200, body)
                    except Exception as e:
                        self._reply(400, json.dumps(
                            {"ok": False,
                             "message": str(e)}).encode())
                elif self.path == ROLE_FWD_PATH:
                    # role-split ingest -> shard handoff (PR 15):
                    # the packed DRH1 batch carries per-op flags the
                    # version-stable Request marshal deliberately
                    # omits (serializable), and the reply shape is
                    # frame-negotiated — acks for write batches,
                    # leaf values for read batches, full v2 events
                    # for the coalesced single-op lane.  Both
                    # directions are stage-metered so the bench gate
                    # can hold the handoff share under the client
                    # JSON share it replaced.
                    try:
                        with tracer.stage("role.handoff_parse"):
                            blobs, opflags, reply = \
                                rolemsg.unpack_fwd_request(
                                    self._body())
                            reqs = []
                            for b, fl in zip(blobs,
                                             opflags.tolist()):
                                r = Request.unmarshal(b)
                                if fl & rolemsg.OP_SERIALIZABLE:
                                    r.serializable = True
                                reqs.append(r)
                        if reply == rolemsg.REPLY_ACKS:
                            res = _refwd_not_leader(
                                server, reqs,
                                server.do_many(reqs, timeout=30.0))
                            with tracer.stage(
                                    "role.handoff_marshal"):
                                out = rolemsg.pack_fwd_acks(
                                    len(res),
                                    {i: (getattr(x, "error_code",
                                                 300), str(x))
                                     for i, x in enumerate(res)
                                     if not isinstance(x, Response)})
                        elif reply == rolemsg.REPLY_VALS:
                            res = server.read_many(reqs,
                                                   timeout=30.0)
                            vals: list = []
                            errs_r: dict = {}
                            for i, x in enumerate(res):
                                if isinstance(x, Response):
                                    ev = x.event
                                    vals.append(
                                        ev.node.value
                                        if ev is not None
                                        and ev.node is not None
                                        else None)
                                else:
                                    vals.append(None)
                                    errs_r[i] = (getattr(
                                        x, "error_code", 300),
                                        str(x))
                            with tracer.stage(
                                    "role.handoff_marshal"):
                                out = rolemsg.pack_fwd_vals(
                                    vals, errs_r)
                        else:
                            # mixed lane: plain GETs ride the
                            # zero-WAL read path (linearizable via
                            # ReadIndex; serializable flag already
                            # restored above), everything else —
                            # writes and QGET quorum reads — goes
                            # through the proposal coalescer; the
                            # two result streams stitch back in
                            # request order
                            ridx = [i for i, r in enumerate(reqs)
                                    if r.method == "GET"
                                    and not r.quorum]
                            widx = [i for i, r in enumerate(reqs)
                                    if r.method != "GET"
                                    or r.quorum]
                            results: list = [None] * len(reqs)
                            if widx:
                                wreqs = [reqs[i] for i in widx]
                                for i, x in zip(
                                        widx, _refwd_not_leader(
                                            server, wreqs,
                                            server.do_many(
                                                wreqs,
                                                timeout=30.0))):
                                    results[i] = x
                            if ridx:
                                for i, x in zip(
                                        ridx, server.read_many(
                                            [reqs[i] for i in ridx],
                                            timeout=30.0)):
                                    results[i] = x
                            final = []
                            for x in results:
                                if isinstance(x, Response):
                                    final.append(
                                        x.event if x.event
                                        is not None else
                                        EtcdError(300, "no event"))
                                else:
                                    final.append(x)
                            with tracer.stage(
                                    "role.handoff_marshal"):
                                out = rolemsg.pack_fwd_response(
                                    final)
                        self._reply(200, out)
                    except ServerStoppedError:
                        self._reply(503, b"")
                    except (FrameError, ValueError) as e:
                        self._reply(400, json.dumps(
                            {"ok": False,
                             "message": str(e)}).encode())
                elif self.path == READ_INDEX_PATH:
                    # PR 7 follower reads: the leader's confirmed
                    # read index for one group (lease answers
                    # instantly; otherwise the request waits in the
                    # batched ReadIndex queue)
                    try:
                        d = json.loads(self._body() or b"{}")
                        rd = server.read_index(int(d.get("group",
                                                         -1)),
                                               timeout=5.0)
                        self._reply(200, json.dumps(
                            {"rd": rd}).encode())
                    except ServerStoppedError:
                        self._reply(503, b"")
                    except (TimeoutError, ValueError) as e:
                        # 200 with an err body: "not leader" is an
                        # answer, not a transport failure — the
                        # keep-alive pool must not tear the socket
                        self._reply(200, json.dumps(
                            {"err": str(e)}).encode())
                elif self.path == GET_MANY_PATH:
                    # PR 7 batched zero-WAL read lane (the GET
                    # analog of propose_many): values ride back so
                    # read-burst drivers (bench, chaos linz gate)
                    # can check what they observed.  Body is a JSON
                    # array of path strings (the compact form — a
                    # read's wire cost is its key), a binary DCB1
                    # path frame (PR 14, magic-sniffed), or a packed
                    # Request batch (flagged reads).
                    try:
                        body = self._body()
                        if body[:1] == b"[":
                            with tracer.stage("client.parse"):
                                reqs = json.loads(body)
                                if not all(isinstance(p, str)
                                           for p in reqs):
                                    raise ValueError(
                                        "path list must be strings")
                        elif body[:4] == b"DCB1":
                            with tracer.stage("client.parse"):
                                reqs = clientmsg.unpack_get_request(
                                    body)
                        else:
                            # flagged reads ride the version-stable
                            # packed batch — ingest cost, like the
                            # propose body
                            with tracer.stage("dist.parse_batch"):
                                reqs = unpack_requests(body)
                        res = server.read_many(reqs, timeout=30.0)
                        vals: list = []
                        errs_b: dict = {}
                        for i, x in enumerate(res):
                            if isinstance(x, Response):
                                ev = x.event
                                vals.append(
                                    ev.node.value if ev is not None
                                    and ev.node is not None
                                    else None)
                            elif isinstance(x, Exception):
                                vals.append(None)
                                errs_b[i] = (getattr(
                                    x, "error_code", 300), str(x))
                            else:
                                # compact path-string entry: the raw
                                # leaf value (None for a directory)
                                vals.append(x)
                        if self._binary_ok():
                            with tracer.stage("client.marshal"):
                                # the codec takes str leaf values
                                # directly and encodes chunk-wise
                                # into the one output buffer; no
                                # bytes() re-copy of a KB-scale body
                                out = clientmsg.pack_get_response(
                                    vals, errs_b)
                            self._reply(200, out,
                                        ctype=clientmsg.CONTENT_TYPE)
                            return
                        with tracer.stage("client.marshal"):
                            out = json.dumps(
                                {"n": len(res), "vals": vals,
                                 "errs": {
                                     str(i): {"errorCode": c,
                                              "message": m}
                                     for i, (c, m)
                                     in errs_b.items()}}).encode()
                        self._reply(200, out)
                    except ServerStoppedError:
                        self._reply(503, b"")
                    except Exception as e:
                        self._reply(400, json.dumps(
                            {"ok": False,
                             "message": str(e)}).encode())
                else:
                    self._reply(404, b"")
            except Exception:
                log.exception("peer handler failed")
                try:
                    self._reply(500, b"")
                except Exception:
                    pass

        def do_GET(self):
            if self.path == "/mraft/faults":
                # active spec + per-(point, action) injection counts
                # (the nemesis replay gate compares these)
                self._reply(200, json.dumps(
                    _faults.FAULTS.snapshot()).encode())
            elif self.path == "/mraft/snapshot":
                self._reply(200, server.snapshot_blob())
            elif self.path == SNAP_FRONTIER_PATH:
                self._reply(200, server.snapshot_frontier())
            elif self.path == "/mraft/obs":
                # JSON registry snapshot (bucket counts + exact ring
                # percentiles): the cross-process merge form —
                # scripts/dist_bench.py pools the three hosts'
                # ack-RTT buckets from here
                self._reply(200, _obs.registry.snapshot_json())
            elif self.path == "/mraft/obs/light":
                # no exact-percentile ring sorts: the role
                # supervisor's per-second scrape form (PR 17)
                self._reply(200,
                            _obs.registry.snapshot_json(light=True))
            elif self.path == "/mraft/obs/flight":
                # flight-recorder dump (PR 8): the ring + clock
                # anchors + per-stage wall/cpu/device sums — what
                # chaos_drill harvests on gate failure and
                # scripts/trace_stitch.py merges across nodes
                self._reply(200, server.flight.dump_json())
            elif self.path == "/mraft/obs/timeseries":
                # windowed-delta ring (PR 17): rates and windowed
                # percentiles over the last ETCD_TS_RETENTION steps
                from ..obs import timeseries as _timeseries

                self._reply(200,
                            _timeseries.start_default()
                            .snapshot_json())
            elif self.path == "/mraft/obs/slo":
                # declared-objective verdict (PR 17): burn rates
                # over the ring — same body as GET /v2/stats/slo
                from ..obs import slo as _slo

                self._reply(200, _slo.default_verdict_json())
            elif self.path == "/mraft/leaders":
                # leadership-transition trace for the chaos drill's
                # recovery decomposition; lock-free reads of small
                # numpy arrays (diagnostic endpoint, torn reads
                # tolerable)
                body = json.dumps({
                    "slot": server.slot,
                    "lead": [bool(x) for x in server.mr.is_leader()],
                    "elected_at":
                        [float(x) for x in server._elected_at],
                    "elected_term":
                        [int(x) for x in server._elected_term],
                    "first_apply_at":
                        [float(x) for x in server._first_apply_at],
                }).encode()
                self._reply(200, body)
            else:
                self._reply(404, b"")

        def _binary_ok(self) -> bool:
            """Negotiation gate: answer in the binary client framing
            only when this server speaks it AND the request's Accept
            header asked for it (a JSON-only client never sees a
            binary byte; a binary client against a JSON-only server
            reads the missing reply Content-Type as 'negotiate
            down')."""
            return (server.wire_binary and clientmsg.CONTENT_TYPE
                    in (self.headers.get("Accept") or ""))

        def _reply(self, code: int, body: bytes,
                   ctype: str | None = None) -> None:
            self.send_response(code)
            if ctype is not None:
                self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

    return Handler
