"""L5 server orchestration (reference etcdserver/)."""

from .cluster import (
    Cluster,
    ClusterStore,
    Member,
    new_member,
    parse_member_id,
)
from .config import CLUSTER_STATE_NEW, ServerConfig
from .sender import new_sender
from .server import (
    DEFAULT_SNAP_COUNT,
    EtcdServer,
    Response,
    ServerStoppedError,
    UnknownMethodError,
    WalSnapStorage,
    gen_id,
    new_server,
)

__all__ = [
    "EtcdServer",
    "Response",
    "ServerConfig",
    "ServerStoppedError",
    "UnknownMethodError",
    "WalSnapStorage",
    "Cluster",
    "ClusterStore",
    "Member",
    "new_member",
    "new_sender",
    "new_server",
    "parse_member_id",
    "gen_id",
    "DEFAULT_SNAP_COUNT",
    "CLUSTER_STATE_NEW",
]
