"""Compartmentalized serving: role-split multi-process topology
(PR 15).

One etcd-tpu "node" becomes a small supervised process tree, the
compartmentalization move from "Scaling Replicated State Machines
with Compartmentalization" — each GIL-bound concern gets its own
process so the serving tier scales with host cores before hosts:

    supervisor (this module, `--role supervise`)
    ├── ingest       stateless client front door + batcher: parses
    │                client wire (JSON/DCB1), coalesces per-shard
    │                lanes, forwards packed DRH1 batches over
    │                peerlink to the LOCAL shard (which runs the
    │                usual leader-forwarding underneath)
    ├── worker       apply/watch fanout: consumes each shard's
    │                committed stream off a shared-memory ring into
    │                a mirror Store and serves watches (wait= client
    │                requests 307 here from the ingest)
    └── shard s ∈ 0..S-1   a full DistServer owning G/S raft groups;
                     shard s peers only with shard s of other hosts
                     (S independent consensus planes)

Port map (every host derives it from the same inputs, so the bench
and drill can address any role of any host):

    shard s peer port   = peer_base_port + m*s      (m = host count)
    ingest client port  = --client-port
    worker watch port   = --client-port + m

Handoff wire forms are the packed DRH1 frames in wire/rolemsg.py;
both directions run under `role.handoff_marshal`/`role.handoff_parse`
stage rows so dist_bench can hold the handoff share under the client
JSON share it replaced.  The shard -> worker committed stream rides
server/shmring.py: cursors live in the shared segment, so a killed
worker resumes exactly at its persisted tail — no replay, no
double-apply (tests/test_roles.py).

Supervision: children die with the supervisor (PDEATHSIG + a ppid
watchdog), and a killed role is respawned with the same arguments;
`<data-dir>/roles.json` maps role -> {pid, port} on every (re)spawn
so the chaos drill's `role_kill` nemesis can pick victims and verify
the replacement.

Documented limitations (by design, scoped to what the drill and
tests exercise): the worker's mirror store rebases event indices
after a worker restart (old waitIndex watches see 401
EventIndexCleared, exactly etcd's history-window semantics), and
recursive reads/watches see only keys whose first path segment
routes to the same shard.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import signal
import subprocess
import sys
import threading
import time
import urllib.request

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import aggregate as _aggregate
from ..obs import metrics as _obs
from ..obs import profiler as _profiler
from ..obs import slo as _slo
from ..obs import timeseries as _timeseries
from ..obs.exporter import (
    CONTENT_TYPE as _PROM_CTYPE,
    render_prometheus_snapshot,
)
from ..obs.flight import FlightRecorder, install_crash_dump
from ..utils.errors import (
    ECODE_RAFT_INTERNAL,
    EtcdError,
    EtcdOverCapacity,
)
from ..utils.trace import tracer
from ..wire import clientmsg, rolemsg
from ..wire.distmsg import FrameError
from .multigroup import group_of
from .peerlink import KeepAlivePool
from .server import Response, apply_request_to_store, gen_id
from .shmring import ShmRing

log = logging.getLogger(__name__)

ROLE_FWD_PATH = "/mraft/role_fwd"

#: committed-stream ring span per shard; at ~100 B/committed entry
#: this buffers seconds of full-rate apply traffic for the worker
RING_BYTES = 1 << 22

#: per-shard ingest lane depth.  Bounded: the front door's admission
#: control (max_inflight 4096 process-wide) saturates long before
#: this, so a full lane only ever means the shard link is wedged —
#: shed loudly rather than queue invisibly.
LANE_DEPTH = 8192

_LANE_MAX_BATCH = 256


def worker_port(client_port: int, m: int) -> int:
    """The apply/watch worker's client port.  Stride by the host
    count: deployments allocate consecutive client ports per host,
    so +m lands every host's worker in a disjoint band."""
    return client_port + m


def shard_peer_urls(peers: list[str], s: int) -> list[str]:
    """Peer base URLs for shard ``s``'s consensus plane: same hosts,
    port strided by the host count."""
    m = len(peers)
    out = []
    for u in peers:
        scheme, _, rest = u.partition("://")
        host, _, port = rest.rpartition(":")
        out.append(f"{scheme}://{host}:{int(port) + m * s}")
    return out


def ring_name(client_port: int, s: int) -> str:
    """Deterministic per-(host, shard) segment name: a respawned
    supervisor reclaims (unlink + recreate) the previous run's
    segments instead of leaking them."""
    return f"etcdtpu_{client_port}_r{s}"


def _arm_parent_death() -> None:
    """Die with the supervisor: the chaos drill SIGKILLs whole nodes
    (leader_kill), and orphaned role processes would squat the
    derived ports and fail the restart.  PDEATHSIG where available,
    plus a portable ppid watchdog."""
    if sys.platform.startswith("linux"):
        try:
            import ctypes

            libc = ctypes.CDLL("libc.so.6", use_errno=True)
            libc.prctl(1, signal.SIGTERM, 0, 0, 0)  # PR_SET_PDEATHSIG
        except Exception:  # pragma: no cover - exotic libc
            pass
    ppid = os.getppid()

    def _watch():
        while True:
            if os.getppid() != ppid:
                os._exit(0)
            time.sleep(0.5)

    threading.Thread(target=_watch, daemon=True,
                     name="ppid-watchdog").start()


def attach_ring(name: str) -> ShmRing:
    """Attach to an existing ring WITHOUT handing it to this
    process's resource tracker: on 3.10 an attaching process
    registers the segment and unlinks it at exit, which would tear
    the ring down under the surviving roles the first time one of
    them restarts."""
    ring = ShmRing(name)
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(ring._shm._name, "shared_memory")
    except Exception:  # pragma: no cover - tracker internals moved
        pass
    return ring


class CommitSink:
    """DistServer.commit_sink adapter: packs each apply round's
    (group, gindex, payload) rows into one COMMIT frame and pushes
    it onto the shard's ring.  ``seq`` restarts with the producer;
    the consumer resyncs via the ring generation."""

    def __init__(self, ring: ShmRing):
        self.ring = ring
        self.seq = 0
        ring.bump_generation()

    def push(self, rows: list[tuple[int, int, bytes]]) -> None:
        self.seq += 1
        self.ring.push(rolemsg.pack_commit(self.seq, rows))


# -- ingest role ------------------------------------------------------------


class _StubStore:
    def __init__(self, remote):
        self._r = remote

    def index(self) -> int:
        return self._r.index()

    def json_stats(self) -> bytes:
        return b"{}"


class _StubStats:
    def to_json(self) -> bytes:
        return b"{}"


class _StubCluster:
    def __init__(self, urls):
        self._urls = urls

    def get(self):
        return self

    def client_urls_all(self) -> list[str]:
        return self._urls


class RemoteEtcd:
    """The ingest role's ``etcd`` seam for FrontDoor: every op is
    coalesced onto a per-shard lane, forwarded as one packed DRH1
    batch to the local shard, and the full v2 events ride back in
    the fixed-row FWD_RESP form — the front door renders them
    exactly as if the store were in-process."""

    def __init__(self, host: str, client_port: int,
                 peers: list[str], slot: int, shards: int,
                 timeout: float = 15.0):
        self.shards = shards
        self.slot = slot
        # local shard s answers on this host's strided peer port
        self.shard_urls = [
            shard_peer_urls(peers, s)[slot] for s in range(shards)]
        self.pool = KeepAlivePool(timeout=timeout)
        self.stopping = False
        # per-LANE etcd_index high-water marks: slot s is written
        # only by lane thread s (a bare shared ``self._index`` max
        # was a check-then-act race across lanes — two interleaved
        # updates could move the published index BACKWARD, and the
        # 429 retry hint with it); readers take the max
        self._hiwat = [0] * max(shards, 1)  # owner: ingest-lanes
        self.store = _StubStore(self)
        self.server_stats = _StubStats()
        self.leader_stats = _StubStats()
        self.cluster_store = _StubCluster(
            [f"http://{host}:{client_port}"])
        self._lanes = []
        for s in range(shards):
            q: queue.Queue = queue.Queue(maxsize=LANE_DEPTH)
            t = threading.Thread(target=self._lane, args=(s, q),
                                 daemon=True,
                                 name=f"ingest-lane-s{s}")
            self._lanes.append((q, t))
            t.start()

    def index(self) -> int:
        return max(self._hiwat)

    def term(self) -> int:
        return 0

    def stop(self) -> None:
        self.stopping = True

    # -- single-op lane ---------------------------------------------------

    def do(self, rr, timeout: float | None = None) -> Response:
        sid = group_of(rr.path, self.shards)
        done = threading.Event()
        box: list = [None]
        try:
            self._lanes[sid][0].put_nowait((rr, box, done))
        except queue.Full:
            raise EtcdOverCapacity(
                cause="ingest lane full", index=self.index(),
                retry_after=1.0) from None
        if not done.wait(timeout if timeout else 30.0):
            raise TimeoutError("shard handoff timed out")
        x = box[0]
        if isinstance(x, Exception):
            raise x
        return x

    def _lane(self, sid: int, q: queue.Queue) -> None:
        while not self.stopping:
            try:
                first = q.get(timeout=0.5)
            except queue.Empty:
                continue
            batch = [first]
            # coalesce whatever queued up behind the head op —
            # batching without added latency (the lane only ever
            # waits on an EMPTY queue)
            while len(batch) < _LANE_MAX_BATCH:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            self._flush(sid, batch)

    def _flush(self, sid: int, batch: list) -> None:
        try:
            with tracer.stage("role.handoff_marshal"):
                frame = rolemsg.pack_fwd_request(
                    [rr.marshal() for rr, _, _ in batch],
                    [rolemsg.OP_SERIALIZABLE if rr.serializable
                     else 0 for rr, _, _ in batch],
                    rolemsg.REPLY_EVENTS)
            out = self.pool.post(("lane", sid),
                                 self.shard_urls[sid],
                                 ROLE_FWD_PATH, frame)
            if out is None or out[0] != 200:
                raise EtcdError(ECODE_RAFT_INTERNAL,
                                f"shard {sid} unreachable")
            with tracer.stage("role.handoff_parse"):
                results = rolemsg.unpack_fwd_response(out[1])
            if len(results) != len(batch):
                raise EtcdError(ECODE_RAFT_INTERNAL,
                                "shard reply count mismatch")
        except Exception as e:
            err = (e if isinstance(e, EtcdError)
                   else EtcdError(ECODE_RAFT_INTERNAL, str(e)))
            for _, box, done in batch:
                box[0] = err
                done.set()
            return
        for (rr, box, done), res in zip(batch, results):
            if isinstance(res, tuple):
                code, cause, eidx = res
                box[0] = EtcdError(code, cause, eidx)
            else:
                if res.etcd_index > self._hiwat[sid]:
                    self._hiwat[sid] = res.etcd_index
                box[0] = Response(event=res)
            done.set()

    # -- batch routes ------------------------------------------------------

    def _forward_batch(self, reqs: list, reply: int
                       ) -> tuple[list, dict]:
        """Partition a client batch by shard, forward each partition
        as one DRH1 frame, merge results back into request order.
        Returns (vals, errs) for REPLY_VALS and (ignored, errs) for
        REPLY_ACKS."""
        parts: dict[int, list[int]] = {}
        for i, rr in enumerate(reqs):
            parts.setdefault(group_of(rr.path, self.shards),
                             []).append(i)
        vals: list = [None] * len(reqs)
        errs: dict[int, tuple[int, str]] = {}
        for sid, idxs in parts.items():
            try:
                with tracer.stage("role.handoff_marshal"):
                    frame = rolemsg.pack_fwd_request(
                        [reqs[i].marshal() for i in idxs],
                        [rolemsg.OP_SERIALIZABLE
                         if reqs[i].serializable else 0
                         for i in idxs], reply)
                out = self.pool.post(("batch", sid),
                                     self.shard_urls[sid],
                                     ROLE_FWD_PATH, frame)
                if out is None or out[0] != 200:
                    raise EtcdError(ECODE_RAFT_INTERNAL,
                                    f"shard {sid} unreachable")
                with tracer.stage("role.handoff_parse"):
                    if reply == rolemsg.REPLY_ACKS:
                        _n, sub = rolemsg.unpack_fwd_acks(out[1])
                    else:
                        svals, sub = rolemsg.unpack_fwd_vals(out[1])
                        for j, i in enumerate(idxs):
                            vals[i] = svals[j]
            except Exception as e:
                code = getattr(e, "error_code", ECODE_RAFT_INTERNAL)
                for i in idxs:
                    errs[i] = (code, str(e))
                continue
            for j, (code, msg) in sub.items():
                errs[idxs[j]] = (code, msg)
        return vals, errs

    def route_propose_many(self, method, path, query, headers,
                           body) -> tuple[int, dict, bytes]:
        try:
            from .distserver import unpack_requests

            with tracer.stage("dist.parse_batch"):
                reqs = unpack_requests(body)
            _, errs = self._forward_batch(reqs, rolemsg.REPLY_ACKS)
            if clientmsg.CONTENT_TYPE in (headers.get("accept")
                                          or ""):
                with tracer.stage("client.marshal"):
                    out = bytes(clientmsg.pack_propose_response(
                        len(reqs), errs))
                return 200, {"Content-Type":
                             clientmsg.CONTENT_TYPE}, out
            with tracer.stage("client.marshal"):
                out = json.dumps(
                    {"n": len(reqs),
                     "errs": {str(i): {"errorCode": c, "message": m}
                              for i, (c, m) in errs.items()}}
                ).encode()
            return 200, {"Content-Type": "application/json"}, out
        except Exception as e:
            return 400, {}, json.dumps(
                {"ok": False, "message": str(e)}).encode()

    def route_get_many(self, method, path, query, headers,
                       body) -> tuple[int, dict, bytes]:
        try:
            from .distserver import unpack_requests
            from ..wire.requests import Request

            if body[:1] == b"[":
                with tracer.stage("client.parse"):
                    paths = json.loads(body)
                    if not all(isinstance(p, str) for p in paths):
                        raise ValueError("path list must be strings")
                    reqs = [Request(method="GET", path=p,
                                    id=gen_id()) for p in paths]
            elif body[:4] == b"DCB1":
                with tracer.stage("client.parse"):
                    reqs = [Request(method="GET", path=p,
                                    id=gen_id())
                            for p in clientmsg.unpack_get_request(
                                body)]
            else:
                with tracer.stage("dist.parse_batch"):
                    reqs = unpack_requests(body)
            vals, errs = self._forward_batch(reqs,
                                             rolemsg.REPLY_VALS)
            svals = [None if v is None else v.decode()
                     for v in vals]
            if clientmsg.CONTENT_TYPE in (headers.get("accept")
                                          or ""):
                with tracer.stage("client.marshal"):
                    out = clientmsg.pack_get_response(svals, errs)
                return 200, {"Content-Type":
                             clientmsg.CONTENT_TYPE}, bytes(out)
            with tracer.stage("client.marshal"):
                out = json.dumps(
                    {"n": len(reqs), "vals": svals,
                     "errs": {str(i): {"errorCode": c, "message": m}
                              for i, (c, m) in errs.items()}}
                ).encode()
            return 200, {"Content-Type": "application/json"}, out
        except Exception as e:
            return 400, {}, json.dumps(
                {"ok": False, "message": str(e)}).encode()


def _obs_routes(flight: FlightRecorder) -> dict:
    """/mraft/obs + /mraft/obs/flight + /mraft/obs/timeseries +
    /mraft/obs/slo for a role process — same shapes the shard's
    peer tier serves, so harvest_rings, the bench stage scraper,
    the chaos forensics dump and scripts/doctor.py address every
    role uniformly."""
    return {
        "/mraft/obs": lambda *a: (
            200, {"Content-Type": "application/json"},
            _obs.registry.snapshot_json()),
        "/mraft/obs/light": lambda *a: (
            200, {"Content-Type": "application/json"},
            _obs.registry.snapshot_json(light=True)),
        "/mraft/obs/flight": lambda *a: (
            200, {"Content-Type": "application/json"},
            flight.dump_json()),
        "/mraft/obs/timeseries": lambda *a: (
            200, {"Content-Type": "application/json"},
            _timeseries.start_default().snapshot_json()),
        "/mraft/obs/slo": lambda *a: (
            200, {"Content-Type": "application/json"},
            _slo.default_verdict_json()),
    }


def _start_role_obs() -> None:
    """Always-on per-role observability: the sampling profiler and
    the windowed-delta ring (both idempotent, both env-gated)."""
    _profiler.start_default()
    _timeseries.start_default()


def run_ingest(args) -> None:
    from .frontdoor import FrontDoorConfig, serve_frontdoor

    _arm_parent_death()
    done = _arm_signals()
    _start_role_obs()
    m = len(args.peers.split(","))
    flight = FlightRecorder(node=f"{args.name}-ingest",
                            slot=args.slot, role="ingest")
    install_crash_dump(flight, args.flight_dir)
    remote = RemoteEtcd("127.0.0.1", args.client_port,
                        args.peers.split(","), args.slot,
                        args.shards)
    routes = {
        "/mraft/propose_many": remote.route_propose_many,
        "/mraft/get_many": remote.route_get_many,
    }
    routes.update(_obs_routes(flight))
    serve_frontdoor(
        remote, "127.0.0.1", args.client_port,
        config=FrontDoorConfig.from_env(os.environ),
        extra_routes=routes,
        watch_redirect="http://127.0.0.1:%d" % worker_port(
            args.client_port, m))
    print("ROLE-READY ingest", flush=True)
    _serve_forever(done, remote.stop)


# -- worker role ------------------------------------------------------------


class WorkerEtcd:
    """The apply/watch worker's ``etcd`` seam: a mirror Store fed by
    the shards' committed streams.  Watches and local reads are
    real; anything needing consensus is refused (clients reach this
    port only via the ingest's watch redirect)."""

    def __init__(self, host: str, port: int):
        from ..store import Store

        self.store = Store()
        self.lock = threading.Lock()
        self.server_stats = _StubStats()
        self.leader_stats = _StubStats()
        self.cluster_store = _StubCluster([f"http://{host}:{port}"])

    def do(self, rr, timeout: float | None = None) -> Response:
        # apply_request_to_store has no GET branch (GETs never ride
        # the committed log) — serve the mirror read directly; store
        # errors (key not found, ...) propagate as EtcdError for the
        # front door to map
        if rr.method == "GET" and not rr.wait:
            with self.lock:
                return Response(event=self.store.get(
                    rr.path, rr.recursive, rr.sorted))
        raise EtcdError(ECODE_RAFT_INTERNAL,
                        "watch worker serves reads and watches only")

    def index(self) -> int:
        return self.store.index()

    def term(self) -> int:
        return 0


def run_worker(args) -> None:
    from .frontdoor import FrontDoorConfig, serve_frontdoor
    from ..wire.requests import Request

    _arm_parent_death()
    done = _arm_signals()
    _start_role_obs()
    m = len(args.peers.split(","))
    port = worker_port(args.client_port, m)
    flight = FlightRecorder(node=f"{args.name}-worker",
                            slot=args.slot, role="worker")
    install_crash_dump(flight, args.flight_dir)
    etcd = WorkerEtcd("127.0.0.1", port)
    rings = [attach_ring(ring_name(args.client_port, s))
             for s in range(args.shards)]
    stop = threading.Event()
    # (shard, group) -> highest applied gindex.  In-memory is
    # enough: the ring's shared tail cursor is the restart cursor —
    # a respawned worker resumes AFTER everything it already
    # consumed, so replay (double-apply) is structurally impossible.
    frontier: dict[tuple[int, int], int] = {}
    last_seq: dict[int, tuple[int, int]] = {}

    def consume() -> None:
        backoff = 0.0002
        while not stop.is_set():
            busy = False
            for sid, ring in enumerate(rings):
                data = ring.pop()
                if data is None:
                    continue
                busy = True
                try:
                    with tracer.stage("role.handoff_parse"):
                        seq, groups, gidx, blobs = \
                            rolemsg.unpack_commit(data)
                except FrameError as e:
                    log.warning("worker: bad commit frame from "
                                "shard %d: %s", sid, e)
                    continue
                gen = ring.generation
                prev = last_seq.get(sid)
                if prev is not None and prev[0] == gen \
                        and seq != prev[1] + 1:
                    # ring overran (or shard skipped): events were
                    # lost for fanout — loud, not fatal (watchers
                    # resync via waitIndex + 401 semantics)
                    log.warning(
                        "worker: commit seq gap from shard %d "
                        "(%d -> %d, %d ring drops)", sid,
                        prev[1], seq, ring.dropped)
                last_seq[sid] = (gen, seq)
                with etcd.lock, etcd.store.fanout_round(), \
                        tracer.stage("role.apply"):
                    for g, gi, blob in zip(groups.tolist(),
                                           gidx.tolist(), blobs):
                        key = (sid, int(g))
                        if int(gi) <= frontier.get(key, -1):
                            continue  # duplicate delivery guard
                        frontier[key] = int(gi)
                        try:
                            apply_request_to_store(
                                etcd.store, Request.unmarshal(blob))
                        except EtcdError:
                            # apply-time verdicts (CAS misses, ...)
                            # already went to the writer via the
                            # shard; the mirror only needs the state
                            pass
                        except Exception:
                            log.exception(
                                "worker: mirror apply failed")
            if busy:
                backoff = 0.0002
            else:
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.002)

    threading.Thread(target=consume, daemon=True,
                     name="worker-consume").start()
    serve_frontdoor(etcd, "127.0.0.1", port,
                    config=FrontDoorConfig.from_env(os.environ),
                    extra_routes=_obs_routes(flight))
    print("ROLE-READY worker", flush=True)
    _serve_forever(done, stop.set)


# -- shard role -------------------------------------------------------------


def run_shard(args) -> None:
    from .distserver import DistServer

    _arm_parent_death()
    done = _arm_signals()
    _start_role_obs()
    s = args.shard_index
    peers = args.peers.split(",")
    g_local = args.groups // args.shards
    srv = DistServer(
        os.path.join(args.data_dir, f"shard{s}"), slot=args.slot,
        peer_urls=shard_peer_urls(peers, s), g=g_local,
        cap=args.cap, name=f"{args.name}-s{s}",
        max_batch_ents=args.max_batch_ents,
        tick_interval=args.tick_interval,
        post_timeout=args.post_timeout,
        election=args.election_ticks,
        pipeline_depth=args.pipeline_depth,
        coalesce_us=args.coalesce_us,
        snap_count=args.snap_count,
        lease_ticks=args.lease_ticks)
    srv.flight.role = f"shard{s}"
    install_crash_dump(srv.flight, args.flight_dir)
    srv.start()
    # committed-stream tap attached AFTER start(): WAL-replay
    # applies recover pre-crash state and must not re-enter the
    # worker's mirror (the ring tail already passed them)
    srv.commit_sink = CommitSink(
        attach_ring(ring_name(args.client_port, s)))
    if args.bootstrap:
        deadline = time.time() + 60.0
        while time.time() < deadline:
            lead = srv.mr.is_leader()
            if lead.all():
                break
            srv._campaign(~lead)
            time.sleep(0.3)
    print(f"ROLE-READY shard{s}", flush=True)
    _serve_forever(done, srv.stop)


def _arm_signals() -> threading.Event:
    """Register the role's stop handler FIRST — install_crash_dump
    chains onto (and re-raises into) the disposition it finds, so
    the order is: dump the flight ring, then stop."""
    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    return done


def _serve_forever(done: threading.Event, on_stop) -> None:
    while not done.is_set():
        done.wait(1.0)
    try:
        on_stop()
    finally:
        os._exit(0)


# -- supervisor -------------------------------------------------------------

ROLES_FILE = "roles.json"


def supervisor_obs_port(client_port: int, m: int) -> int:
    """The supervisor's merged-plane port.  Hosts already occupy
    the [client, client+m) ingest and [client+m, client+2m) worker
    bands; +2m lands every host's supervisor in a third disjoint
    band."""
    return client_port + 2 * m


class SupervisorObs:
    """The supervisor's merged observability plane (PR 17
    tentpole): one scrape thread pulls every child role's
    ``/mraft/obs`` snapshot into a
    :class:`~..obs.aggregate.MetricsAggregator` (stale-marked,
    monotone across respawns), feeds the merged cumulative view
    through a supervisor-level time-series ring, and serves:

    - ``/metrics`` — one Prometheus exposition of every role with a
      ``role`` label (0.0.4-conformant, HELP/TYPE once per family);
    - ``/mraft/obs`` — the merged JSON view + per-role liveness;
    - ``/mraft/obs/timeseries`` — the merged windowed-delta ring;
    - ``/mraft/obs/slo`` and ``/v2/stats/slo`` — the cluster-level
      SLO verdict evaluated over the merged ring;
    - ``/mraft/roles`` — role -> {port, up, stale_s} discovery for
      scripts/doctor.py.

    A down/mid-respawn child never yields a scrape error from these
    endpoints: its last-known samples stay, ``etcd_role_up`` drops
    to 0, and the next incarnation folds in monotone."""

    def __init__(self, targets: dict[str, int], port: int,
                 interval: float | None = None,
                 stale_after: float = _aggregate.STALE_AFTER_S,
                 self_registry: _obs.Registry | None = None,
                 host: str = "127.0.0.1"):
        self.targets = dict(targets)
        self.port = port
        if interval is None:
            try:
                interval = float(os.environ.get(
                    "ETCD_OBS_SCRAPE_S") or 1.0)
            except ValueError:
                interval = 1.0
        self.interval = interval
        self.host = host
        self._self_reg = self_registry
        self.agg = _aggregate.MetricsAggregator(
            stale_after=stale_after)
        self.ts = _timeseries.TimeSeries(self.agg.merged_families,
                                         step=interval)
        self.slo = _slo.SLOEvaluator(self.ts,
                                     registry=self_registry)
        self._stop = threading.Event()
        self._httpd: ThreadingHTTPServer | None = None

    # -- scraping ---------------------------------------------------------

    def scrape_once(self, timeout: float = 1.5) -> None:
        """One scrape round over every child, then one ring step
        over the merged view.  Child failures are absorbed (counted,
        stale-marked) — the merged plane never errors with them."""
        for role, port in sorted(self.targets.items()):
            # the light form: no exact-percentile ring sorts on the
            # child — the merge only consumes count/sum/buckets,
            # and the scrape runs every second on a shared core
            url = f"http://{self.host}:{port}/mraft/obs/light"
            try:
                with urllib.request.urlopen(url, timeout=timeout) \
                        as resp:
                    snap = json.loads(resp.read())
                self.agg.observe(role, snap)
                outcome = "ok"
            except Exception:
                self.agg.scrape_failed(role)
                outcome = "error"
            if self._self_reg is not None:
                self._self_reg.counter(
                    "etcd_obs_scrape_total", role=role,
                    outcome=outcome).inc()
        if self._self_reg is not None:
            # the supervisor is itself a role in the merged view
            self.agg.observe("supervisor",
                             self._self_reg.snapshot(light=True))
        self.ts.step_once()

    def _scrape_loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scrape_once()
            except Exception:  # pragma: no cover - defensive
                log.exception("roles: supervisor scrape failed")

    # -- serving ----------------------------------------------------------

    def roles_body(self) -> bytes:
        live = self.agg.roles()
        body = {role: dict(port=port, **live.get(role, {}))
                for role, port in self.targets.items()}
        body["supervisor"] = {"port": self.port, "up": True}
        return (json.dumps({"roles": body}, sort_keys=True)
                + "\n").encode()

    def _make_handler(self):
        obs = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # pragma: no cover - quiet
                pass

            def do_GET(self):
                try:
                    ctype = "application/json"
                    if self.path == "/metrics":
                        body = render_prometheus_snapshot(
                            obs.agg.merged_families())
                        ctype = _PROM_CTYPE
                    elif self.path == "/mraft/obs":
                        body = obs.agg.merged_json()
                    elif self.path == "/mraft/obs/timeseries":
                        body = obs.ts.snapshot_json()
                    elif self.path in ("/mraft/obs/slo",
                                       "/v2/stats/slo"):
                        body = obs.slo.verdict_json()
                    elif self.path == "/mraft/roles":
                        body = obs.roles_body()
                    else:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception:  # pragma: no cover - conn died
                    pass

        return Handler

    def start(self) -> "SupervisorObs":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True,
                         name="supervisor-obs-http").start()
        threading.Thread(target=self._scrape_loop, daemon=True,
                         name="supervisor-obs-scrape").start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


class Supervisor:
    """Spawns and nurses the role tree for one host slot."""

    def __init__(self, args):
        self.args = args
        self.m = len(args.peers.split(","))
        self.children: dict[str, subprocess.Popen] = {}
        self.ports: dict[str, int] = {}
        self.rings: list[ShmRing] = []
        self.stopping = False
        self._spawned_at: dict[str, float] = {}
        self.obs: SupervisorObs | None = None

    def role_names(self) -> list[str]:
        return (["ingest", "worker"]
                + [f"shard{s}" for s in range(self.args.shards)])

    def _child_argv(self, role: str) -> list[str]:
        a = self.args
        argv = [sys.executable, "-m", "etcd_tpu.server.roles",
                "--role", {"ingest": "ingest",
                           "worker": "worker"}.get(role, "shard"),
                "--data-dir", a.data_dir, "--slot", str(a.slot),
                "--peers", a.peers,
                "--client-port", str(a.client_port),
                "--shards", str(a.shards),
                "--groups", str(a.groups), "--cap", str(a.cap),
                "--name", a.name,
                "--max-batch-ents", str(a.max_batch_ents),
                "--pipeline-depth", str(a.pipeline_depth),
                "--coalesce-us", str(a.coalesce_us),
                "--lease-ticks", str(a.lease_ticks),
                "--election-ticks", str(a.election_ticks),
                "--tick-interval", str(a.tick_interval),
                "--post-timeout", str(a.post_timeout),
                "--flight-dir", a.flight_dir]
        if a.snap_count is not None:
            argv += ["--snap-count", str(a.snap_count)]
        if role.startswith("shard"):
            argv += ["--shard-index", role[5:]]
            if a.bootstrap and role not in self._spawned_at:
                argv += ["--bootstrap"]
        return argv

    def _port_of(self, role: str) -> int:
        a = self.args
        if role == "ingest":
            return a.client_port
        if role == "worker":
            return worker_port(a.client_port, self.m)
        s = int(role[5:])
        base = a.peers.split(",")[a.slot]
        return int(base.rpartition(":")[2]) + self.m * s

    def spawn(self, role: str) -> None:
        argv = self._child_argv(role)
        self.children[role] = subprocess.Popen(argv)
        self.ports[role] = self._port_of(role)
        self._spawned_at[role] = time.monotonic()
        self._write_roles_file()
        log.info("roles: spawned %s pid=%d port=%d", role,
                 self.children[role].pid, self.ports[role])

    def _write_roles_file(self) -> None:
        path = os.path.join(self.args.data_dir, ROLES_FILE)
        tmp = path + ".tmp"
        body = {r: {"pid": p.pid, "port": self.ports[r]}
                for r, p in self.children.items()}
        if self.obs is not None:
            body["supervisor"] = {"pid": os.getpid(),
                                  "port": self.obs.port}
        with open(tmp, "w") as f:
            json.dump(body, f)
        os.replace(tmp, path)

    def start(self) -> None:
        os.makedirs(self.args.data_dir, exist_ok=True)
        for s in range(self.args.shards):
            name = ring_name(self.args.client_port, s)
            # reclaim any segment a SIGKILLed previous supervisor
            # left behind — deterministic names make the leak
            # self-healing
            try:
                ShmRing(name).unlink()
            except (FileNotFoundError, ValueError, FrameError):
                pass
            self.rings.append(ShmRing(name, capacity=RING_BYTES,
                                      create=True))
        for role in self.role_names():
            self.spawn(role)
        try:
            self.obs = SupervisorObs(
                dict(self.ports),
                supervisor_obs_port(self.args.client_port, self.m),
                self_registry=_obs.registry).start()
            self._write_roles_file()  # now carries the supervisor
        except OSError as e:
            # the merged plane is additive — a squatted obs port
            # must never take the serving tree down with it
            log.warning("roles: merged obs plane unavailable: %s",
                        e)
            self.obs = None

    def wait_ready(self, timeout: float = 90.0) -> bool:
        """Every role port answers (and, with --bootstrap, every
        shard leads all its groups)."""
        deadline = time.time() + timeout
        probes = {
            r: (f"http://127.0.0.1:{self._port_of(r)}"
                + ("/mraft/leaders" if r.startswith("shard")
                   else "/v2/machines"))
            for r in self.role_names()}
        pending = dict(probes)
        while time.time() < deadline:
            for r, u in list(pending.items()):
                try:
                    with urllib.request.urlopen(u, timeout=2.0) \
                            as resp:
                        body = resp.read()
                except Exception:
                    continue
                if r.startswith("shard") and self.args.bootstrap:
                    try:
                        if not all(json.loads(body)["lead"]):
                            continue
                    except Exception:
                        continue
                del pending[r]
            if not pending:
                return True
            time.sleep(0.2)
        log.warning("roles: not ready after %.0fs: %s", timeout,
                    sorted(pending))
        return False

    def run(self) -> None:
        """Nurse loop: respawn dead children until stopped."""
        while not self.stopping:
            for role, proc in list(self.children.items()):
                if proc.poll() is None or self.stopping:
                    continue
                age = time.monotonic() - self._spawned_at[role]
                log.warning("roles: %s (pid %d) exited rc=%s after "
                            "%.1fs; respawning", role, proc.pid,
                            proc.returncode, age)
                if age < 0.5:
                    time.sleep(0.5)  # crash-loop damper
                self.spawn(role)
            time.sleep(0.2)

    def stop(self) -> None:
        self.stopping = True
        if self.obs is not None:
            self.obs.stop()
        for proc in self.children.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 5.0
        for proc in self.children.values():
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
        for ring in self.rings:
            ring.close()
            ring.unlink()


def supervise(args) -> None:
    _profiler.start_default()
    sup = Supervisor(args)

    def _term(signum, frame):
        sup.stop()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    sup.start()
    sup.wait_ready()
    print("READY", flush=True)
    try:
        sup.run()
    finally:
        sup.stop()


# -- CLI --------------------------------------------------------------------


def make_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="etcd_tpu.server.roles")
    ap.add_argument("--role", required=True,
                    choices=["supervise", "ingest", "worker",
                             "shard"])
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--slot", type=int, required=True)
    ap.add_argument("--peers", required=True,
                    help="comma-separated slot-indexed peer base "
                         "URLs (shard 0 plane; shard s strides by "
                         "the host count)")
    ap.add_argument("--client-port", type=int, required=True)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--shard-index", type=int, default=0)
    ap.add_argument("--groups", type=int, default=8,
                    help="TOTAL groups across shards (must divide "
                         "evenly)")
    ap.add_argument("--cap", type=int, default=64)
    ap.add_argument("--name", default="dist")
    ap.add_argument("--max-batch-ents", type=int, default=32)
    ap.add_argument("--pipeline-depth", type=int, default=8)
    ap.add_argument("--coalesce-us", type=int, default=2000)
    ap.add_argument("--lease-ticks", type=int, default=30)
    ap.add_argument("--election-ticks", type=int, default=60)
    ap.add_argument("--tick-interval", type=float, default=0.05)
    ap.add_argument("--post-timeout", type=float, default=2.0)
    ap.add_argument("--snap-count", type=int, default=None)
    ap.add_argument("--flight-dir", default="trace_artifacts")
    ap.add_argument("--bootstrap", action="store_true")
    return ap


def main(argv=None) -> None:
    ap = make_parser()
    args = ap.parse_args(argv)
    if args.groups % args.shards:
        ap.error(f"--groups {args.groups} must divide by "
                 f"--shards {args.shards}")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s " + args.role + " %(message)s")
    if args.role == "supervise":
        supervise(args)
    elif args.role == "ingest":
        run_ingest(args)
    elif args.role == "worker":
        run_worker(args)
    else:
        run_shard(args)


if __name__ == "__main__":
    main()
