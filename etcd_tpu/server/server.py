"""Server orchestration: the hub tying Node+WAL+snap+store+sender
together (reference etcdserver/server.go).

One apply-loop thread runs the reference's ``run()`` select loop
(server.go:247-323): tick the raft clock, pull Ready batches, persist
HardState+entries BEFORE sending messages (the durability contract),
apply committed entries to the store, trigger waiting clients, fire
snapshots every ``snap_count`` applies, and propose leader SYNCs that
expire TTL keys deterministically cluster-wide.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..raft import Node, Peer, STATE_LEADER, restart_node, start_node
from ..snap import NoSnapshotError, Snapshotter
from ..store import Store, Watcher
from ..utils.backoff import Backoff
from ..utils.errors import EtcdError, EtcdNoSpace
from ..utils.trace import tracer
from ..utils.wait import Wait
from ..wal import WAL, TornTailError, exist as wal_exist
from ..wire import (
    CONF_CHANGE_ADD_NODE,
    CONF_CHANGE_REMOVE_NODE,
    ConfChange,
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    HardState,
    MSG_APP,
    Message,
    Snapshot,
    is_empty_snap,
)
from ..wire.requests import Info, Request
from .cluster import ATTRIBUTES_SUFFIX, Cluster, ClusterStore, Member
from .stats import LeaderStats, ServerStats
from .config import ServerConfig
from .sender import new_sender

log = logging.getLogger(__name__)

from ..obs import metrics as _obs  # noqa: E402  (stdlib-only module)

# classic-tier read accounting (PR 7): plain GETs here are
# local-replica (serializable) serves — see the do() comment
_M_READ_SERIALIZABLE = _obs.registry.counter(
    "etcd_read_serve_total", path="serializable", outcome="ok")

DEFAULT_SYNC_TIMEOUT = 1.0
DEFAULT_SNAP_COUNT = 10000  # reference server.go:29
DEFAULT_PUBLISH_RETRY_INTERVAL = 5.0

TICK_INTERVAL = 0.1       # reference server.go:182
SYNC_INTERVAL = 0.5       # reference server.go:183
ELECTION_TICKS = 10       # reference server.go:136,168
HEARTBEAT_TICKS = 1


class UnknownMethodError(Exception):
    pass


class ServerStoppedError(Exception):
    pass


def gen_id() -> int:
    """Random nonzero 63-bit id (reference server.go:575-580)."""
    n = 0
    while n == 0:
        n = random.getrandbits(63)
    return n


@dataclass
class Response:
    """Reference server.go:45-49."""

    event: object | None = None
    watcher: Optional[Watcher] = None
    err: Exception | None = None


def apply_request_to_store(store: Store, r: Request) -> Response:
    """Map a committed Request onto a store call (reference
    server.go:503-540); shared by the single-group server and the
    co-hosted multi-group server (multigroup.py)."""
    expr = r.expiration / 1e9 if r.expiration else None

    def f(call):
        try:
            return Response(event=call())
        except EtcdError as e:
            return Response(err=e)

    if r.method == "POST":
        return f(lambda: store.create(r.path, r.dir, r.val, True, expr))
    if r.method == "PUT":
        exists, exists_set = r.prev_exist, r.prev_exist is not None
        if exists_set:
            if exists:
                return f(lambda: store.update(r.path, r.val, expr))
            return f(lambda: store.create(r.path, r.dir, r.val, False,
                                          expr))
        if r.prev_index > 0 or r.prev_value != "":
            return f(lambda: store.compare_and_swap(
                r.path, r.prev_value, r.prev_index, r.val, expr))
        return f(lambda: store.set(r.path, r.dir, r.val, expr))
    if r.method == "DELETE":
        if r.prev_index > 0 or r.prev_value != "":
            return f(lambda: store.compare_and_delete(
                r.path, r.prev_value, r.prev_index))
        return f(lambda: store.delete(r.path, r.dir, r.recursive))
    if r.method == "QGET":
        # through-the-log quorum read: counted at apply — every
        # replica applies the entry, so per-host stats attribute the
        # replication cost, not just the origin's serve (PR 7 split)
        store.stats.inc_read_path("quorum")
        return f(lambda: store.get(r.path, r.recursive, r.sorted))
    if r.method == "SYNC":
        store.delete_expired_keys(r.time / 1e9)
        return Response()
    return Response(err=UnknownMethodError(r.method))


class WalSnapStorage:
    """The Storage seam (reference server.go:51-62): WAL + snapshotter
    behind one interface so the device-backed replay path can swap in."""

    def __init__(self, wal: WAL, snapshotter: Snapshotter):
        self.wal = wal
        self.snapshotter = snapshotter

    def save(self, st: HardState, ents) -> None:
        """MUST block until st and ents are on stable storage."""
        self.wal.save(st, ents)

    def save_snap(self, snap: Snapshot) -> None:
        self.snapshotter.save_snap(snap)

    def cut(self) -> None:
        self.wal.cut()

    def probe_space(self) -> None:
        """NOSPACE recovery probe (PR 10): raises EtcdNoSpace while
        the disk still refuses."""
        self.wal.probe_space()

    def gc(self, index: int) -> int:
        """Segment GC behind the DURABLE snapshot window (PR 6): the
        run loop calls this right after ``save_snap`` returns — the
        snapshotter fsyncs file+dir before returning, so the
        delete-after-fsync ordering holds.  The boundary is the
        OLDEST retained snapshot (not ``index``, the newest): the
        corrupt-newest fallback ladder needs WAL coverage from
        whichever kept snapshot load() lands on."""
        floor = self.snapshotter.retained_floor()
        return self.wal.gc(index if floor is None
                           else min(index, floor))


class EtcdServer:
    """Reference server.go:191-218."""

    def __init__(self, *, store: Store, node: Node, id: int,
                 attributes: dict, storage, send: Callable,
                 cluster_store: ClusterStore,
                 snap_count: int = DEFAULT_SNAP_COUNT,
                 tick_interval: float = TICK_INTERVAL,
                 sync_interval: float = SYNC_INTERVAL,
                 leader_stats: LeaderStats | None = None):
        self.store = store
        self.node = node
        self.id = id
        self.attributes = attributes
        self.storage = storage
        self.send = send
        self.cluster_store = cluster_store
        self.snap_count = snap_count or DEFAULT_SNAP_COUNT
        self.tick_interval = tick_interval
        self.sync_interval = sync_interval

        self.w = Wait()
        self.done = threading.Event()
        self._thread: threading.Thread | None = None
        self._publish_thread: threading.Thread | None = None
        self.raft_index = 0
        self.raft_term = 0
        # NOSPACE read-only mode (PR 10): a persist that hits
        # EtcdNoSpace HOLDS its Ready — the Ready contract (persist
        # before send) is preserved by simply not advancing: no
        # messages leave, nothing applies, writes are rejected with
        # errorCode 405, and the held Ready is re-persisted at probe
        # cadence until the disk takes it.  The node just experiences
        # a very slow disk.
        self._nospace = False
        self._held_ready = None
        self._nospace_backoff = Backoff(base=0.25, cap=5.0,
                                        site="nospace_probe")
        self._nospace_probe_t = 0.0
        self._m_nospace = _obs.registry.gauge("etcd_nospace_active")
        self.server_stats = ServerStats(
            attributes.get("Name", ""), id)
        self.leader_stats = leader_stats or LeaderStats(id)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Reference server.go:223-241."""
        self._start()
        self._publish_thread = threading.Thread(
            target=self.publish, args=(DEFAULT_PUBLISH_RETRY_INTERVAL,),
            daemon=True)
        self._publish_thread.start()

    def _start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.node.stop()
        self.done.set()
        # the apply loop itself calls stop() on should_stop
        # (server.go:295-298); a thread cannot join itself
        if self._thread is not None \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        # release the fanout dispatcher/delivery threads AFTER the
        # apply loop joined — a batch it submits mid-shutdown must
        # still dispatch (close drains the queue before exiting; a
        # close-then-submit would strand events).  getattr: test
        # scaffolds build bare servers without a store
        st = getattr(self, "store", None)
        if st is not None:
            st.fanout.close()

    # -- raft message input ------------------------------------------------

    def process(self, m: Message) -> None:
        """Peer /raft endpoint feeds here (server.go:243-245)."""
        if m.type == MSG_APP:
            self.server_stats.recv_append()
        self.node.step(m)

    # -- the apply loop ----------------------------------------------------

    def run(self) -> None:
        """Reference server.go:247-323."""
        is_leader = False
        snapi = 0
        appliedi = 0
        nodes: list[int] = []
        next_tick = time.monotonic() + self.tick_interval
        next_sync = time.monotonic() + self.sync_interval

        while not self.done.is_set():
            now = time.monotonic()
            if now >= next_tick:
                self.node.tick()
                next_tick = now + self.tick_interval
            if is_leader and now >= next_sync:
                # no SYNC proposals while read-only: the node's
                # in-memory log must not outgrow a WAL that cannot
                # take records (same guard as the dist/multigroup
                # tiers)
                if not self._nospace:
                    self.sync(DEFAULT_SYNC_TIMEOUT)
                next_sync = now + self.sync_interval

            wait_for = min(next_tick - now,
                           (next_sync - now) if is_leader else
                           self.tick_interval)
            if self._nospace and self._held_ready is None \
                    and time.monotonic() >= self._nospace_probe_t:
                # snapshot-triggered NOSPACE (no Ready to hold):
                # probe the disk directly
                try:
                    probe = getattr(self.storage, "probe_space",
                                    None)
                    if probe is not None:
                        probe()
                    self._exit_nospace()
                except EtcdNoSpace as e:
                    self._enter_nospace(None, e)
            if self._held_ready is not None:
                # NOSPACE hold: don't pop further Readys (the node's
                # unsent messages and unapplied commits queue behind
                # this one); retry the held persist at probe cadence
                if time.monotonic() < self._nospace_probe_t:
                    self.done.wait(max(min(wait_for, 0.05), 0.001))
                    continue
                rd = self._held_ready
            else:
                rd = self.node.ready(timeout=max(wait_for, 0.001))
                if rd is None:
                    continue

            # persist BEFORE send (the Ready contract, node.go:41-60)
            try:
                with tracer.stage("server.persist"):
                    self.storage.save(rd.hard_state, rd.entries)
                    self.storage.save_snap(rd.snapshot)
                    if not is_empty_snap(rd.snapshot):
                        # the snapshot just became durable (file +
                        # dir fsync inside save_snap): segments
                        # wholly behind it are dead weight — GC
                        # here, never before the fsync
                        # (delete-after-fsync rule).  getattr: the
                        # Storage seam is duck-typed and test
                        # recorders predate gc()
                        gc = getattr(self.storage, "gc", None)
                        if gc is not None:
                            gc(rd.snapshot.index)
            except EtcdNoSpace as e:
                self._enter_nospace(rd, e)
                continue
            if self._held_ready is not None:
                self._exit_nospace()
            for m in rd.messages:
                if m.type == MSG_APP:
                    self.server_stats.send_append()
            with tracer.stage("server.send"):
                self.send(rd.messages)

            # one fanout dispatch per committed batch: mutations only
            # queue their events; match + watcher delivery happen on
            # the engine's thread after this block (PR 9)
            with tracer.stage("server.apply"), self.store.fanout_round():
                for e in rd.committed_entries:
                    if e.type == ENTRY_NORMAL:
                        r = Request.unmarshal(e.data)
                        self.w.trigger(r.id, self.apply_request(r))
                    elif e.type == ENTRY_CONF_CHANGE:
                        cc = ConfChange.unmarshal(e.data)
                        self.apply_conf_change(cc)
                        self.w.trigger(cc.id, None)
                    else:  # pragma: no cover
                        raise AssertionError("unexpected entry type")
                    self.raft_index = e.index
                    self.raft_term = e.term
                    appliedi = e.index

            if rd.soft_state is not None:
                nodes = rd.soft_state.nodes
                is_leader = rd.soft_state.raft_state == STATE_LEADER
                self.server_stats.set_state(
                    rd.soft_state.raft_state, rd.soft_state.lead)
                if rd.soft_state.should_stop:
                    self.stop()
                    return

            if rd.snapshot.index > snapi:
                snapi = rd.snapshot.index

            # recover from snapshot if it is more updated than applied
            # (server.go:306-311)
            if rd.snapshot.index > appliedi:
                self.store.recovery(rd.snapshot.data)
                appliedi = rd.snapshot.index

            if appliedi - snapi > self.snap_count:
                try:
                    self.snapshot(appliedi, nodes)
                except EtcdNoSpace as e:
                    # no Ready to hold here — just go read-only and
                    # probe; the snapshot trigger re-fires once
                    # space returns
                    self._enter_nospace(None, e)
                snapi = appliedi

    # -- NOSPACE read-only mode (PR 10) ------------------------------------

    def _enter_nospace(self, rd, e: EtcdNoSpace) -> None:
        if rd is not None:
            self._held_ready = rd
        if not self._nospace:
            self._nospace = True
            self._nospace_backoff.reset()
            self._m_nospace.set(1)
            log.error("etcdserver: ENTERING NOSPACE read-only mode "
                      "(%s): writes rejected with errorCode 405, "
                      "reads keep serving", e.cause)
        self._nospace_probe_t = (time.monotonic()
                                 + self._nospace_backoff.next())

    def _exit_nospace(self) -> None:
        self._held_ready = None
        if self._nospace:
            self._nospace = False
            self._nospace_backoff.reset()
            self._m_nospace.set(0)
            log.warning("etcdserver: NOSPACE recovered — accepting "
                        "writes again")

    # -- client request path -----------------------------------------------

    def do(self, r: Request, timeout: float | None = None) -> Response:
        """Propose writes/quorum-GETs through raft; serve plain
        GET/watch locally (reference server.go:337-380)."""
        if r.id == 0:
            raise ValueError("r.id cannot be 0")
        if r.method == "GET" and r.quorum:
            r.method = "QGET"
        if r.method in ("POST", "PUT", "DELETE", "QGET"):
            if self._nospace:
                # read-only NOSPACE mode: the distinct error code,
                # not a timeout (reads below still serve)
                raise EtcdNoSpace(
                    cause="member is read-only (NOSPACE)")
            data = r.marshal()
            ch = self.w.register(r.id)
            try:
                self.node.propose(data, timeout=timeout)
            except TimeoutError:
                self.w.trigger(r.id, None)  # GC wait
                raise
            import queue as _q

            try:
                x = ch.get(timeout=timeout)
            except _q.Empty:
                self.w.trigger(r.id, None)  # GC wait
                raise TimeoutError("request timed out")
            if x is None:
                # stop, a GC'd registration, or a duplicate request
                # id whose channel was already consumed (Chan is
                # one-shot: later receivers observe closure)
                if self.done.is_set():
                    raise ServerStoppedError()
                raise TimeoutError("request superseded")
            resp = x
            if resp.err is not None:
                raise resp.err
            return resp
        if r.method == "GET":
            if r.wait:
                wc = self.store.watch(r.path, r.recursive, r.stream,
                                      r.since)
                return Response(watcher=wc)
            # the classic tier keeps reference read semantics: a
            # plain GET serves the local replica, which on a
            # follower is a SERIALIZABLE read — counted as such so
            # the per-path split stays honest (linearizable reads
            # on this tier go through ?quorum=true; the zero-WAL
            # lease/ReadIndex machinery lives on the dist tier)
            _M_READ_SERIALIZABLE.inc()
            self.store.stats.inc_read_path("serializable")
            ev = self.store.get(r.path, r.recursive, r.sorted)
            return Response(event=ev)
        raise UnknownMethodError(r.method)

    # -- membership --------------------------------------------------------

    def add_member(self, memb: Member, timeout: float | None = None) -> None:
        """Reference server.go:382-395."""
        cc = ConfChange(id=gen_id(), type=CONF_CHANGE_ADD_NODE,
                        node_id=memb.id,
                        context=json.dumps(memb.to_dict()).encode())
        self._configure(cc, timeout)

    def remove_member(self, id: int, timeout: float | None = None) -> None:
        cc = ConfChange(id=gen_id(), type=CONF_CHANGE_REMOVE_NODE,
                        node_id=id)
        self._configure(cc, timeout)

    def _configure(self, cc: ConfChange,
                   timeout: float | None = None) -> None:
        """Reference server.go:417-433."""
        ch = self.w.register(cc.id)
        try:
            self.node.propose_conf_change(cc, timeout=timeout)
        except TimeoutError:
            self.w.trigger(cc.id, None)
            raise
        import queue as _q

        try:
            ch.get(timeout=timeout)
        except _q.Empty:
            self.w.trigger(cc.id, None)
            raise TimeoutError("conf change timed out")

    # -- RaftTimer ---------------------------------------------------------

    def index(self) -> int:
        return self.raft_index

    def term(self) -> int:
        return self.raft_term

    # -- periodic work -----------------------------------------------------

    def sync(self, timeout: float) -> None:
        """Leader-only SYNC proposal carrying wall time: applied
        deterministically as DeleteExpiredKeys cluster-wide
        (reference server.go:438-456)."""
        req = Request(method="SYNC", id=gen_id(),
                      time=int(time.time() * 1e9))
        data = req.marshal()

        def bg():
            try:
                self.node.propose(data, timeout=timeout)
            except (TimeoutError, Exception):
                pass

        threading.Thread(target=bg, daemon=True).start()

    def publish(self, retry_interval: float) -> None:
        """Register server attributes under its member key
        (reference server.go:463-491)."""
        b = json.dumps(self.attributes)
        req = Request(id=gen_id(), method="PUT",
                      path=Member(id=self.id).store_key()
                      + ATTRIBUTES_SUFFIX,
                      val=b)
        while not self.done.is_set():
            try:
                self.do(req, timeout=retry_interval)
                log.info("etcdserver: published %s to the cluster",
                         self.attributes)
                return
            except ServerStoppedError:
                return
            except Exception as e:
                log.warning("etcdserver: publish error: %s", e)
                req.id = gen_id()

    # -- apply -------------------------------------------------------------

    def apply_request(self, r: Request) -> Response:
        """Map a committed Request onto a store call
        (reference server.go:503-540)."""
        return apply_request_to_store(self.store, r)

    def apply_conf_change(self, cc: ConfChange) -> None:
        """Reference server.go:542-559."""
        self.node.apply_conf_change(cc)
        if cc.type == CONF_CHANGE_ADD_NODE:
            m = Member.from_dict(json.loads(cc.context))
            if cc.node_id != m.id:
                raise AssertionError("unexpected nodeID mismatch")
            self.cluster_store.add(m)
        elif cc.type == CONF_CHANGE_REMOVE_NODE:
            self.cluster_store.remove(cc.node_id)
        else:  # pragma: no cover
            raise AssertionError("unexpected ConfChange type")

    def snapshot(self, snapi: int, snapnodes: list[int]) -> None:
        """Store snapshot -> raft compaction -> WAL cut
        (reference server.go:562-571)."""
        with tracer.span("server.snapshot"):
            d = self.store.save()
            self.node.compact(snapi, snapnodes, d)
            self.storage.cut()


# In "auto" mode the batched device replay only pays off once the WAL
# is big enough to amortize the jit compile (~seconds); below this the
# host lane wins.  The threshold lives with the router (which also
# gates its own device probe on it) so both stay in lockstep.
from ..wal.backend_policy import (  # noqa: E402
    DEVICE_MIN_BYTES as _DEVICE_REPLAY_MIN_BYTES,
)


def _replay_wal_raw(waldir: str, index: int, backend: str,
                    stage: str = "restart"):
    """WAL replay honoring --storage-backend through the measured
    backend router (wal/backend_policy): the router picks native-host
    / device / streaming-device per its startup probe, ``stage``
    names the decision in the obs registry and the policy snapshot
    (bench rows attribute regressions to routing vs kernel).  The
    fast lane keeps entries as an un-materialized ``EntryBlock``
    (struct-of-arrays — the form array-based consumers like
    gereplay.scan feed on); the repair-capable host path yields an
    Entry list."""
    if backend != "host":
        from .. import native
        from ..wal.backend_policy import get_policy

        size = sum(
            os.path.getsize(os.path.join(waldir, f))
            for f in os.listdir(waldir))
        pol = get_policy()
        route = pol.route(stage, size_bytes=size,
                          strict_device=(backend == "tpu"))
        env_forced = pol.decisions[stage]["why"].startswith("env ")
        # the host-routed fused native scan beats the pure-Python
        # decoder at every size; the device lanes keep the old
        # amortization threshold (jit compile is seconds) unless the
        # operator's env override demands them
        use_fast = (backend == "tpu" or env_forced
                    or size >= _DEVICE_REPLAY_MIN_BYTES
                    or (route == "host" and native.available()))
        if use_fast:
            try:
                from ..wal.replay_device import open_replay_device

                with tracer.span("replay.device"):
                    w, md, hard_state, block = open_replay_device(
                        waldir, index, route=route)
                log.info("etcdserver: %s-route replay of %d entries "
                         "(%d bytes)", route, len(block), size)
                return w, md, hard_state, block
            except Exception as e:
                # a crash-torn tail must heal on EVERY backend — the
                # torn bytes were never acked — so even strict tpu
                # mode falls through to the host path's repair for
                # that case; all three scanners raise the same typed
                # TornTailError (wal/errors.py), so this matches on
                # type, never on message text
                if backend == "tpu" and not isinstance(
                        e, TornTailError):
                    raise
                log.warning("etcdserver: %s-route replay failed; "
                            "falling back to host path", route,
                            exc_info=True)
                # the decision artifact must name the lane that RAN
                pol.note(stage, "host",
                         f"{route} lane failed "
                         f"({type(e).__name__}); host repair path")
    with tracer.span("replay.host"):
        w = WAL.open_at_index(waldir, index)
        # server restarts tolerate a crash-torn tail (unacked by
        # construction — acks only follow fsync); the device lane
        # above raises on one, and auto mode then lands here
        md, hard_state, ents = w.read_all(repair=True)
    return w, md, hard_state, ents


def _replay_wal(waldir: str, index: int, backend: str):
    """WAL replay honoring --storage-backend (the north-star seam:
    same (metadata, state, entries) out of either execution path)."""
    from ..wal.replay_device import EntryBlock

    w, md, hard_state, out = _replay_wal_raw(waldir, index, backend)
    if isinstance(out, EntryBlock):
        out = out.entries()
    return w, md, hard_state, out


def new_server(cfg: ServerConfig, *, discoverer=None,
               post_fn=None) -> EtcdServer:
    """Bootstrap/restart split (reference server.go:87-188)."""
    cfg.verify()
    snapdir = os.path.join(cfg.data_dir, "snap")
    os.makedirs(snapdir, mode=0o700, exist_ok=True)
    crc_fn = None
    if getattr(cfg, "storage_backend", "auto") != "host":
        try:  # device hash for large snapshot blobs; host otherwise
            from ..ops.crc_kernel import auto_crc32c

            crc_fn = auto_crc32c
        except ImportError:
            log.warning("etcdserver: jax unavailable; host snapshot "
                        "hashing")
    from ..snap import DEFAULT_SNAP_KEEP

    ss = Snapshotter(snapdir, crc_fn=crc_fn,
                     keep=int(os.environ.get("ETCD_SNAP_KEEP",
                                             DEFAULT_SNAP_KEEP)))
    st = Store()
    # watch fanout runs on its own delivery stage so the apply loop
    # never blocks on watcher queues (PR 9; ETCD_WATCH_WORKERS scales
    # delivery threads)
    st.fanout.start()
    m = cfg.cluster.find_name(cfg.name)
    waldir = os.path.join(cfg.data_dir, "wal")

    if not wal_exist(waldir):
        if cfg.discovery_url:
            if discoverer is None:
                from ..discovery import Discoverer

                discoverer = Discoverer(cfg.discovery_url, m.id,
                                        str(cfg.cluster))
            s = discoverer.discover()
            cfg.cluster.set_from_string(s)
        elif cfg.cluster_state != "new":
            raise RuntimeError(
                "initial cluster state unset and no wal or discovery "
                "URL found")
        w = WAL.create(waldir, Info(id=m.id).marshal())
        peers = [Peer(id=id, context=json.dumps(
            cfg.cluster[id].to_dict()).encode())
            for id in cfg.cluster.ids()]
        n = start_node(m.id, peers, ELECTION_TICKS, HEARTBEAT_TICKS)
    else:
        if cfg.discovery_url:
            log.warning(
                "etcd: ignoring discovery URL: etcd has already been "
                "initialized and has a valid log in %s", waldir)
        index = 0
        snapshot = None
        try:
            snapshot = ss.load()
        except NoSnapshotError:
            pass
        if snapshot is not None:
            log.info("etcdserver: restart from snapshot at index %d",
                     snapshot.index)
            st.recovery(snapshot.data)
            index = snapshot.index
        w, md, hard_state, ents = _replay_wal(
            waldir, index, getattr(cfg, "storage_backend", "auto"))
        info = Info.unmarshal(md or b"")
        if info.id != m.id:
            raise RuntimeError(
                f"unexpected nodeid {info.id:x}, want {m.id:x}")
        n = restart_node(m.id, ELECTION_TICKS, HEARTBEAT_TICKS, snapshot,
                         hard_state, ents)

    cls = ClusterStore(st)
    lstats = LeaderStats(m.id)
    return EtcdServer(
        store=st,
        node=n,
        id=m.id,
        attributes={"Name": cfg.name,
                    "ClientURLs": cfg.client_urls},
        storage=WalSnapStorage(w, ss),
        send=new_sender(cls, post_fn=post_fn, leader_stats=lstats,
                        tls_info=getattr(cfg, "peer_tls", None)),
        leader_stats=lstats,
        cluster_store=cls,
        snap_count=cfg.snap_count,
    )
