"""Peer connection plumbing shared by the classic sender and the
dist tier (PR 5): one keep-alive connection cache for synchronous
request/response POSTs, and the striped PIPELINED channel the
windowed append pipeline rides.

Both exist because a fresh TCP connect per frame costs more than the
frame itself at intra-DC latencies (the distserver keep-alive cache
proved this in PR 2; this module is that cache promoted to a shared
abstraction, plus the pipelining the lockstep round could not use).

Delivery contract (both forms): AT-LEAST-ONCE.  A retry or a
reconnect cannot tell "the peer closed the idle socket before my
bytes arrived" from "the peer processed the POST and the response was
lost", so a processed frame may be re-sent.  Every payload routed
through here must be idempotent at the receiver (raft append/vote
frames are prefix-verified and term-guarded; snapshot pulls are
reads) — do NOT route a non-idempotent peer operation through this
module without adding a dedup key at the receiver.
"""

from __future__ import annotations

import http.client
import logging
import queue
import socket
import threading
from collections import deque
from urllib.parse import urlparse

from ..utils import faults as _faults
from ..utils.backoff import Backoff

log = logging.getLogger(__name__)


class KeepAlivePool:
    """Keyed cache of keep-alive HTTP(S) connections.

    ``post(key, url, ...)`` POSTs over the cached connection for
    ``key``; a send on a connection the peer closed between calls
    retries ONCE on a fresh connection (counted in ``reconnects`` —
    the classic sender bills these to its peer-send failure family).
    The cache entry is POPPED for the duration of the call:
    concurrent callers racing on one key each get their own
    connection, and the store-back closes any connection another
    caller parked meanwhile.  A changed ``url`` for a cached key
    (runtime membership swap, a test's network cut) drops the stale
    connection instead of short-circuiting the new route.
    """

    def __init__(self, timeout: float = 1.0, ssl_context=None,
                 keep_statuses: tuple[int, ...] = (200, 204),
                 on_reconnect=None):
        self.timeout = timeout
        self.ssl_context = ssl_context
        self.keep_statuses = keep_statuses
        self._conns: dict[object, tuple[str, object]] = {}
        self._lock = threading.Lock()
        self.reconnects = 0  # stale-cached-socket retry events
        self._on_reconnect = on_reconnect

    def _connect(self, u):
        if u.scheme == "https":
            return http.client.HTTPSConnection(
                u.hostname, u.port, timeout=self.timeout,
                context=self.ssl_context)
        return http.client.HTTPConnection(
            u.hostname, u.port, timeout=self.timeout)

    def post(self, key, url: str, path: str,
             payload) -> tuple[int, bytes] | None:
        """POST ``payload`` to ``url + path``; returns
        ``(status, body)`` or None when both attempts failed (a
        dropped message, by contract)."""
        u = urlparse(url)
        with self._lock:
            held_url, conn = self._conns.pop(key, (None, None))
        if conn is not None and held_url != url:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        cached = conn is not None
        for attempt in range(2):
            if conn is None:
                conn = self._connect(u)
            try:
                conn.request(
                    "POST", path, body=payload,
                    headers={"Content-Type":
                             "application/octet-stream"})
                resp = conn.getresponse()
                out = resp.read()
                if resp.status in self.keep_statuses:
                    with self._lock:
                        prev = self._conns.get(key)
                        self._conns[key] = (url, conn)
                    if prev is not None:  # racing caller parked one
                        try:
                            prev[1].close()
                        except Exception:
                            pass
                else:
                    conn.close()
                return resp.status, out
            except (http.client.HTTPException, OSError,
                    ConnectionError):
                try:
                    conn.close()
                except Exception:
                    pass
                conn = None
                if cached and attempt == 0:
                    # the parked socket had gone stale under us
                    with self._lock:
                        self.reconnects += 1
                    if self._on_reconnect is not None:
                        self._on_reconnect()
        return None

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for _url, conn in conns:
            try:
                conn.close()
            except Exception:
                pass


def _read_http_response(rf) -> tuple[int, bytes, bool]:
    """Parse one HTTP/1.1 response off a buffered reader.  Returns
    (status, body, keep) where ``keep`` is False when the server
    asked to close.  Raises ConnectionError on EOF/short reads."""
    line = rf.readline(65536)
    if not line:
        raise ConnectionError("EOF before status line")
    parts = line.split(None, 2)
    if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
        raise ConnectionError(f"bad status line {line[:64]!r}")
    status = int(parts[1])
    clen = 0
    keep = True
    while True:
        h = rf.readline(65536)
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            raise ConnectionError("EOF in headers")
        k, _, v = h.partition(b":")
        k = k.strip().lower()
        if k == b"content-length":
            clen = int(v)
        elif k == b"connection" and b"close" in v.lower():
            keep = False
    body = rf.read(clen) if clen else b""
    if len(body) != clen:
        raise ConnectionError("short body")
    return status, body, keep


class _Stripe:
    """One pipelined socket: requests written ahead, responses read
    back in order and FIFO-matched to their seq tags."""

    __slots__ = ("sock", "rf", "pending", "cond", "gen", "dead", "q",
                 "backoff")

    def __init__(self):
        self.sock = None
        self.rf = None
        self.pending: deque = deque()  # (seq, payload_len) FIFO
        self.cond = threading.Condition()
        self.gen = 0      # bumped per (re)connect
        self.dead = True
        self.q: queue.Queue = queue.Queue()
        # reconnect pacing (PR 10): the first retry after a healthy
        # stretch is free, then jittered-exponential up to 5s.
        # Reset ONLY when the reader parses a real response — under
        # a persistent one-way partition connect() keeps succeeding
        # while responses never come, and the old flat 50ms wait
        # became a tight connect/teardown churn loop at read_timeout
        # cadence.
        self.backoff = Backoff(base=0.05, cap=5.0, site="peerlink",
                               first_zero=True)


class PipeChannel:
    """Striped pipelined HTTP/1.1 POST channel to ONE peer.

    The caller tags each payload with a ``seq``; up to the caller's
    window of requests ride each stripe ahead of their responses
    (true wire pipelining — the reason the channel speaks raw sockets
    instead of http.client, whose per-response buffered makefile
    cannot be safely interleaved).  Per stripe, responses return in
    request order, so the FIFO pending deque matches them back to
    seqs; ACROSS stripes they interleave arbitrarily — the pipeline
    layer matches on the frame's own (epoch, seq) tag and tolerates
    reordering.

    Each stripe owns its OWN send queue (``send(..., stripe=s)``):
    the pipeline partitions raft GROUPS across stripes, so one lane's
    frames always ride one connection in order — striping adds
    parallel sockets without reordering any single group's appends
    (cross-stripe reordering only ever interleaves INDEPENDENT
    lanes).

    ``on_resp(seq, status, body)`` fires on a reader thread.
    ``on_fail(seqs, reason)`` fires with every seq whose response can
    no longer arrive (connect failure, send failure, read error/
    timeout) — the pipeline treats those as dropped frames and falls
    back to probe-and-resend, so at-least-once redelivery is the
    worst case, never silent loss.

    ``on_sent(seq)`` (optional) fires on the writer thread right
    after the frame's bytes hit the socket — the accurate send edge
    the trace stitcher's clock alignment wants (the caller registers
    the frame BEFORE queueing it, but the writer may drain later
    under load; stamping at registration would fold queue wait into
    the network hop).

    ``fault_ctx=(src, dst)`` (optional) names the link for the
    ``peerlink.send`` failpoint (utils/faults): ``drop`` loses the
    frame SILENTLY — not registered as pending, no on_fail — so only
    the caller's in-flight expire sweep recovers it (the gray-loss
    case the sweep exists for); ``corrupt`` flips one payload byte;
    ``err`` reads as a send failure.
    """

    def __init__(self, url: str, path: str, *, stripes: int = 1,
                 timeout: float = 1.0, read_timeout: float | None = None,
                 ssl_context=None, on_resp=None, on_fail=None,
                 on_sent=None, name: str = "",
                 fault_ctx: tuple[str, str] | None = None):
        self.url = url
        u = urlparse(url)
        self._host, self._port = u.hostname, u.port
        self._tls = u.scheme == "https"
        self._path = path
        self.timeout = timeout
        # a pipelined response sits behind every request ahead of it:
        # give the reader more rope than one synchronous round trip
        self.read_timeout = (read_timeout if read_timeout is not None
                             else 4.0 * timeout)
        self._ssl = ssl_context
        self._on_resp = on_resp or (lambda seq, status, body: None)
        self._on_fail = on_fail or (lambda seqs, reason: None)
        self._on_sent = on_sent
        self._fault_ctx = fault_ctx or (None, None)
        self._closed = threading.Event()
        self.stripes = max(1, stripes)
        self._stripes = [_Stripe() for _ in range(self.stripes)]
        self._threads = []
        for i, st in enumerate(self._stripes):
            w = threading.Thread(
                target=self._writer, args=(st,), daemon=True,
                name=f"pipe-{name}-w{i}")
            r = threading.Thread(
                target=self._reader, args=(st,), daemon=True,
                name=f"pipe-{name}-r{i}")
            self._threads += [w, r]
            w.start()
            r.start()

    # -- caller side ------------------------------------------------------

    def send(self, seq: int, payload, stripe: int = 0) -> None:
        """Enqueue one tagged request on stripe ``stripe``
        (non-blocking; the window is the caller's responsibility)."""
        self._stripes[stripe % self.stripes].q.put((seq, payload))

    def queued(self) -> int:
        return sum(st.q.qsize() for st in self._stripes)

    def close(self) -> None:
        self._closed.set()
        for st in self._stripes:
            st.q.put(None)
            self._teardown(st, "closed")
            # the writer may have exited on the sentinel (or long
            # ago, on closed) without draining: frames still QUEUED
            # were never sent and never registered as pending — fail
            # them too, or the caller's in-flight window leaks shut
            # permanently (found as a post-partition-heal wedge: the
            # rebuilt channel's predecessor swallowed one probe
            # frame and the peer never heard the new term)
            leftover = []
            while True:
                try:
                    item = st.q.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    leftover.append(item[0])
            if leftover:
                self._on_fail(leftover, "closed")

    # -- internals --------------------------------------------------------

    def _teardown(self, st: _Stripe, reason: str,
                  gen: int | None = None) -> None:
        """Kill the stripe's socket and fail its pending frames.
        ``gen`` guards against double-teardown races (reader and
        writer both seeing the same dead socket).  on_fail fires
        OUTSIDE st.cond — the callback takes the server lock, and a
        server-lock holder may be closing this channel (lock-order
        discipline: never hold cond while taking the server lock)."""
        with st.cond:
            if gen is not None and st.gen != gen:
                return
            failed = [seq for seq, _ in st.pending]
            st.pending.clear()
            st.dead = True
            st.gen += 1
            sock, rf = st.sock, st.rf
            st.sock = st.rf = None
            st.cond.notify_all()
        for f in (rf, sock):
            if f is not None:
                try:
                    f.close()
                except Exception:
                    pass
        if failed:
            self._on_fail(failed, reason)

    def _connect(self, st: _Stripe) -> bool:
        try:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._tls and self._ssl is not None:
                sock = self._ssl.wrap_socket(
                    sock, server_hostname=self._host)
            sock.settimeout(self.read_timeout)
            rf = sock.makefile("rb")
        except OSError:
            return False
        with st.cond:
            st.sock, st.rf = sock, rf
            st.dead = False
            st.gen += 1
            st.cond.notify_all()
        return True

    def _writer(self, st: _Stripe) -> None:
        while not self._closed.is_set():
            item = st.q.get()
            if item is None:
                return
            if self._closed.is_set():
                # close() raced our dequeue: its leftover-drain can
                # no longer see this frame, so the no-silent-loss
                # guarantee is ours to keep — fail it, don't drop it
                self._on_fail([item[0]], "closed")
                return
            seq, payload = item
            # peerlink.send failpoint (PR 10): silent loss / byte
            # corruption / injected send error, per [src->dst]
            try:
                act = _faults.hit("peerlink.send",
                                  src=self._fault_ctx[0],
                                  dst=self._fault_ctx[1])
            except OSError:
                self._on_fail([seq], "fault")
                continue
            if act == _faults.DROP:
                # SILENT loss: never registered as pending, no
                # on_fail — exactly the gray failure the caller's
                # expire sweep exists to recover
                continue
            if act == _faults.CORRUPT:
                payload = _faults.flip_byte(payload)
            if st.dead:
                # reconnect pacing (shared jittered backoff): one
                # free immediate retry after a healthy stretch, then
                # exponential — reset only by a parsed response, so
                # a one-way partition (connect works, responses
                # never come) cannot hot-loop connect/teardown
                d = st.backoff.next()
                if d > 0:
                    self._closed.wait(d)
                    if self._closed.is_set():
                        self._on_fail([seq], "closed")
                        return
                if not self._connect(st):
                    self._on_fail([seq], "reconnect")
                    continue
            head = (f"POST {self._path} HTTP/1.1\r\n"
                    f"Host: {self._host}:{self._port}\r\n"
                    f"Content-Type: application/octet-stream\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"\r\n").encode()
            with st.cond:
                dead = st.dead
                if not dead:
                    sock = st.sock
                    # registered BEFORE bytes hit the wire: the
                    # reader must know the seq when the response
                    # races back
                    st.pending.append((seq, len(payload)))
                    st.cond.notify_all()
            if dead:
                self._on_fail([seq], "reconnect")
                continue
            try:
                # sendall OUTSIDE the cond: a blocked send must not
                # stop the reader from draining responses (that
                # deadlock is the whole window at depth > socket
                # buffer)
                sock.sendall(head)
                sock.sendall(payload)
            except OSError:
                self._teardown(st, "reconnect")
                continue
            if self._on_sent is not None:
                self._on_sent(seq)

    def _reader(self, st: _Stripe) -> None:
        while not self._closed.is_set():
            with st.cond:
                while (not self._closed.is_set()
                       and (st.dead or not st.pending)):
                    st.cond.wait(0.5)
                if self._closed.is_set():
                    return
                rf, gen = st.rf, st.gen
            try:
                status, body, keep = _read_http_response(rf)
            except (OSError, ValueError, ConnectionError):
                self._teardown(st, "reconnect", gen=gen)
                continue
            # a real response arrived: the link is healthy — re-arm
            # the writer's reconnect pacing from zero
            st.backoff.reset()
            with st.cond:
                if st.gen != gen:
                    continue  # raced a teardown; seqs already failed
                seq = st.pending.popleft()[0] if st.pending else None
            if not keep or status != 200:
                # server asked to close, or errored: drop the socket
                # (a non-200 peer may be a zombie handler thread of a
                # stopped server still holding the old connection —
                # reconnecting is what reaches its restarted
                # successor on the same address, the keep-alive
                # cache's close-on-error rule applied to the pipe)
                self._teardown(st, "reconnect", gen=gen)
            if seq is not None:
                self._on_resp(seq, status, body)


__all__ = ["KeepAlivePool", "PipeChannel"]
