"""Windowed append-pipeline bookkeeping for the dist tier (PR 5
tentpole).

The lockstep leader round (one frame per peer, one HTTP round trip,
absorb, repeat) serialized four latencies per committed batch:
leader fsync -> send -> follower fsync -> response.  Raft permits a
leader to keep MANY uncommitted append frames in flight per follower
and to overlap its own fsync with the sends (the standard
pipelining/batching port, arXiv:1905.10786 §4); this module is the
per-peer state machine that makes that safe over a drop-tolerant
transport:

- every append frame carries ``(epoch, seq)``: seq numbers frames
  per peer, epoch is bumped whenever the local leadership set
  changes, so late acks from a previous reign can NEVER touch
  progress state (``stale_epoch``);
- acks may return out of order (striped connections) and are matched
  to the exact in-flight frame; unknown/duplicate seqs are counted
  and dropped (``stale_seq``) — match_index only ever advances off a
  matched ack, and monotonically (the engine's progress_update is a
  max);
- per peer the pipe is REPLICATE (window of ``depth`` frames in
  flight, next_ advanced optimistically at send), PROBE (ONE frame
  in flight, entered on a reject or a transport failure: after a
  follower detects a gap and rejects, exactly one catch-up frame
  probes from the repair point instead of a window of doomed
  resends), or SNAPSHOT (PR 6: every lane the leader could send the
  peer sits behind the compaction point, so NO append window can
  help — one need-snap notification frame in flight at heartbeat
  cadence while the peer streams the snapshot; a positive ack must
  NOT reopen the window, only a pump that observes the peer past the
  compaction point does).

This object is pure bookkeeping — no I/O, no locks.  Every method is
called under the owning server's lock; the deterministic pipeline
tests drive it directly.
"""

from __future__ import annotations

REPLICATE = "replicate"
PROBE = "probe"
SNAPSHOT = "snapshot"


class FrameMeta:
    """One in-flight append frame's accounting record."""

    __slots__ = ("seq", "epoch", "t0", "nbytes", "has_ents", "stripe",
                 "traced", "n_ents")

    def __init__(self, seq: int, epoch: int, t0: float, nbytes: int,
                 has_ents: bool, stripe: int, n_ents: int = 0):
        self.seq = seq
        self.epoch = epoch
        self.t0 = t0
        self.nbytes = nbytes
        self.has_ents = has_ents
        self.stripe = stripe
        # entries across all lanes of the frame: the multi-group
        # fusion evidence (PR 14) — inflight_entries() exposes the
        # window's entry depth, not just its frame count
        self.n_ents = n_ents
        # the frame carries a distributed-trace block (PR 8): its
        # matched ack is a flight-recorder frame event (the
        # send/ack half of the stitcher's clock-alignment pairs)
        self.traced = False


class _PeerPipe:
    __slots__ = ("next_seq", "inflight", "mode", "last_send")

    def __init__(self):
        self.next_seq = 1
        self.inflight: dict[int, FrameMeta] = {}
        self.mode = REPLICATE
        # per-STRIPE send stamps: heartbeat cadence is judged per
        # stripe, because each stripe's frames reset election timers
        # only on ITS lanes — one stripe's heartbeat must not
        # satisfy the other's deadline
        self.last_send: dict[int, float] = {}


class AppendPipeline:
    """Per-peer windowed send-stream state (module docstring)."""

    def __init__(self, m: int, slot: int, depth: int):
        if depth < 1:
            raise ValueError(f"pipeline depth {depth} must be >= 1")
        self.depth = depth
        self.epoch = 1  # owner: distpipe-state
        self._peers = {p: _PeerPipe() for p in range(m) if p != slot}  # owner: distpipe-state

    # -- send side --------------------------------------------------------

    def can_send(self, peer: int) -> bool:
        pp = self._peers[peer]
        if pp.mode != REPLICATE:  # PROBE and SNAPSHOT: one in flight
            return not pp.inflight
        return len(pp.inflight) < self.depth

    def register(self, peer: int, *, t0: float, nbytes: int,  # owner: distpipe-state
                 has_ents: bool, stripe: int,
                 n_ents: int = 0) -> FrameMeta:
        """Allocate the next seq for ``peer`` and record the frame as
        in flight; the caller stamps (seq, epoch) into the frame and
        hands it to the transport."""
        pp = self._peers[peer]
        seq = pp.next_seq
        pp.next_seq = (seq + 1) & 0x7FFFFFFF or 1
        meta = FrameMeta(seq, self.epoch, t0, nbytes, has_ents,
                         stripe, n_ents)
        pp.inflight[seq] = meta
        pp.last_send[stripe] = t0
        return meta

    def last_send(self, peer: int, stripe: int = 0) -> float:
        return self._peers[peer].last_send.get(stripe, 0.0)

    def inflight(self, peer: int) -> int:
        return len(self._peers[peer].inflight)

    def inflight_entries(self, peer: int) -> int:
        """Entries (not frames) in the peer's window — how much the
        multi-group fusion amortizes each frame's fixed cost."""
        return sum(m.n_ents
                   for m in self._peers[peer].inflight.values())

    def inflight_total(self) -> int:
        return sum(len(pp.inflight) for pp in self._peers.values())

    def mode(self, peer: int) -> str:
        return self._peers[peer].mode

    # -- ack side ---------------------------------------------------------

    def ack(self, peer: int, seq: int,  # owner: distpipe-state
            epoch: int) -> tuple[str, FrameMeta | None]:
        """Match one response to its in-flight frame.  Returns
        ``("ok", meta)`` or ``(reason, None)`` where reason is
        ``stale_epoch`` (response from a previous leadership reign —
        its progress content must NOT be absorbed) or ``stale_seq``
        (duplicate or already-failed frame)."""
        if epoch != self.epoch:
            return "stale_epoch", None
        meta = self._peers[peer].inflight.pop(seq, None)
        if meta is None:
            return "stale_seq", None
        return "ok", meta

    def note_reject(self, peer: int) -> bool:  # owner: distpipe-state
        """A lane in a matched response rejected: the follower found
        a gap (out-of-order or dropped frame).  Collapse to PROBE so
        the repair goes out as ONE catch-up frame, not a window of
        doomed optimistic sends.  A SNAPSHOT peer stays SNAPSHOT —
        it is behind the compaction point, so probing cannot repair
        it either; only the install can.  Returns True when the mode
        actually changed (the caller records the transition in the
        flight ring)."""
        pp = self._peers[peer]
        if pp.mode in (SNAPSHOT, PROBE):
            return False
        pp.mode = PROBE
        return True

    def note_ok(self, peer: int) -> bool:  # owner: distpipe-state
        """A matched response appended cleanly: (re)open the window.
        SNAPSHOT is sticky here by design: a need-snap lane acks
        POSITIVELY at its commit (distmember.handle_append), so an
        ok ack proves nothing about the peer having crossed the
        compaction point — only :meth:`note_caught_up` (called when a
        pump-time build shows no need-snap lanes) reopens the
        window.  Returns True on an actual transition."""
        pp = self._peers[peer]
        if pp.mode in (SNAPSHOT, REPLICATE):
            return False
        pp.mode = REPLICATE
        return True

    def note_snapshot(self, peer: int) -> bool:  # owner: distpipe-state
        """Every sendable lane for this peer is behind the leader's
        compaction point: stop building append windows (they would
        all be doomed need-snap frames) and hold one notification
        frame in flight at heartbeat cadence until the peer's
        streamed install lands.  Returns True on an actual
        transition."""
        pp = self._peers[peer]
        if pp.mode == SNAPSHOT:
            return False
        pp.mode = SNAPSHOT
        return True

    def note_caught_up(self, peer: int) -> bool:  # owner: distpipe-state
        """A pump-time build_append saw the peer past the compaction
        point again (its streamed install landed and the positive
        need-snap ack advanced match/next): leave SNAPSHOT via ONE
        confirming probe frame rather than a full optimistic window
        against a freshly-installed log.  Returns True on an actual
        transition."""
        pp = self._peers[peer]
        if pp.mode != SNAPSHOT:
            return False
        pp.mode = PROBE
        return True

    def fail(self, peer: int, seqs) -> list[FrameMeta]:  # owner: distpipe-state
        """Transport failure: the listed frames will never be acked.
        Pops them, enters PROBE (SNAPSHOT peers stay SNAPSHOT — a
        lost notification frame changes nothing about the peer being
        behind the compaction point); the caller rolls ``next_`` back
        to ``match + 1`` (DistMember.probe_reset) and the next pump
        sends one probe frame from the confirmed point."""
        pp = self._peers[peer]
        popped = [pp.inflight.pop(s) for s in seqs
                  if s in pp.inflight]
        if popped and pp.mode != SNAPSHOT:
            pp.mode = PROBE
        return popped

    def expire(self, now: float,  # owner: distpipe-state
               max_age: float) -> dict[int, list[FrameMeta]]:
        """Backstop sweep: frames in flight longer than ``max_age``
        can no longer be trusted to ack or fail (a transport edge
        case that lost both).  Pops them per peer and enters PROBE —
        the caller rolls next_ back and resends.  Safe because
        redelivery is at-least-once by contract; a late ack for an
        expired seq reads stale_seq and is dropped."""
        out: dict[int, list[FrameMeta]] = {}
        for peer, pp in self._peers.items():
            stale = [s for s, m in pp.inflight.items()
                     if now - m.t0 > max_age]
            if stale:
                out[peer] = [pp.inflight.pop(s) for s in stale]
                if pp.mode != SNAPSHOT:
                    pp.mode = PROBE
        return out

    # -- leadership transitions -------------------------------------------

    def bump_epoch(self) -> int:  # owner: distpipe-state
        """The local leadership set changed (won or lost lanes): all
        in-flight frames belong to the old reign.  Drop them, bump
        the epoch (so their late acks read stale_epoch), and re-probe
        every peer.  Returns how many frames were dropped."""
        dropped = 0
        self.epoch = (self.epoch + 1) & 0x7FFFFFFF or 1
        for pp in self._peers.values():
            dropped += len(pp.inflight)
            pp.inflight.clear()
            pp.mode = PROBE
        return dropped


__all__ = ["AppendPipeline", "FrameMeta", "PROBE", "REPLICATE",
           "SNAPSHOT"]
