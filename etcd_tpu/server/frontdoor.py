"""Event-driven multi-tenant front door (PR 12).

The stdlib threaded HTTP server pins one thread per connection — a
PR-9 keepalive watch stream holds its thread for minutes, so 50k
watchers would need 50k threads and an overloaded client degrades
every tenant at once.  This module replaces the client-facing serving
loop with a selectors-based event loop that OWNS client connections
and is the single place overload policy lives:

- **Bounded memory at scale**: one loop thread multiplexes every
  connection (watch streams ride :class:`~..store.fanout.WatchMux`
  sinks, not threads); per-connection state is a few KiB of slotted
  buffers.
- **Per-tenant isolation**: requests carry a tenant (header
  ``X-Etcd-Tenant``, else the first ``/v2/keys`` path segment, else
  ``default``); each tenant gets a token bucket (rate/burst, writes
  cost more than reads so writes shed first — the NOSPACE read-only
  shape, per tenant) plus inflight and watch-count quotas.
- **Fail-fast admission**: a request the bucket or a global
  inflight / queue-depth ceiling rejects is answered *immediately*
  with a typed 429 (``errorCode`` 406) + ``Retry-After`` — shedding
  is an answer, never a timeout.  Decision table: admit /
  shed_write / shed_all / close (connection ceiling).

Consensus, the store, and the peer tier are untouched: admitted
requests still flow through the ``api/http.py`` parse seam
(:func:`~..api.http.parse_request`) into ``etcd.do`` on a bounded
worker pool.  The ops plane (``/metrics``, ``/v2/stats``,
``/v2/machines``, CORS preflight) is served inline on the loop and is
exempt from admission — you can always observe an overloaded node.

Threading model (single ownership): ONLY the loop thread touches
connection state.  Workers and fanout delivery threads hand results
back through a completions mailbox + wakeup pipe; watch sinks kick
the loop at most once per drain (``_ConnSink.kicked``), so a burst of
100k events costs one wakeup, not 100k.
"""

from __future__ import annotations

import heapq
import json
import logging
import math
import os
import queue
import re
import selectors
import socket
import threading
import time
import urllib.parse

from ..obs import metrics as _obs
from ..store import clean_path
from ..store.fanout import WatchMux
from ..utils import faults as _faults
from ..utils.errors import (
    ECODE_INVALID_FIELD,
    ECODE_INVALID_FORM,
    ECODE_RAFT_INTERNAL,
    EtcdError,
    EtcdOverCapacity,
)
from .server import gen_id

log = logging.getLogger(__name__)

#: Listen backlog for every client-facing listener (front door AND the
#: threaded fallback in api/http.py).  The stdlib socketserver default
#: is ``request_queue_size = 5``: a connection burst RSTs in the
#: kernel before admission control can even say 429.  Centralized here
#: so the peer/client asymmetry (the peer tier already used 128)
#: cannot reappear.
LISTEN_BACKLOG = 1024

TENANT_HEADER = "x-etcd-tenant"
#: distinct tenants that get their own ``etcd_tenant_inflight`` label
#: before further tenants aggregate under ``_other`` (CATALOG-bounded
#: cardinality — an abusive client minting tenant names must not mint
#: time series)
TENANT_LABEL_MAX = 64
#: distinct tenant *states* (buckets/quotas) tracked before further
#: tenants share one overflow state — bounded memory under a tenant
#: name flood
TENANT_STATE_MAX = 4096

_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 4 * 1024 * 1024
#: per-connection outbound buffer cap; a consumer lagging this far is
#: evicted (slow-consumer policy, same shape as watcher eviction)
MAX_OUT_BYTES = 8 * 1024 * 1024
#: bytes read per readiness callback, so one firehose connection
#: cannot monopolize the loop
READ_QUANTUM = 256 * 1024

_M_CONNS = _obs.registry.gauge("etcd_conns_open")


def _admit_counter(outcome: str, reason: str):
    return _obs.registry.counter("etcd_admission_total",
                                 outcome=outcome, reason=reason)


def parse_tenant(headers: dict, path: str) -> str:
    """Tenant grammar: validated ``X-Etcd-Tenant`` header wins; else
    the first ``/v2/keys`` path segment (a namespace-per-prefix
    convention); else ``default``.  Anything failing the
    ``[A-Za-z0-9._-]{1,64}`` shape falls back — an invalid name must
    not become a distinct bucket."""
    hdr = headers.get(TENANT_HEADER, "")
    if hdr and _TENANT_RE.match(hdr):
        return hdr
    if path.startswith("/v2/keys"):
        seg = path[len("/v2/keys"):].lstrip("/").split("/", 1)[0]
        if seg and _TENANT_RE.match(seg):
            return seg
    return "default"


class TokenBucket:
    """Monotonic-clock token bucket.  ``take`` refills from elapsed
    monotonic time with negative elapsed clamped to zero — a clock
    that jitters backward (VM migration, NTP step on a non-monotonic
    source fed in tests) can pause refill but never mints tokens and
    never goes negative.  A failed take consumes nothing."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float,
                 now: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = time.monotonic() if now is None else now

    def _refill(self, now: float) -> None:
        elapsed = now - self._last
        if elapsed > 0:
            self.tokens = min(self.burst,
                              self.tokens + elapsed * self.rate)
        self._last = now

    def take(self, cost: float, now: float | None = None) -> bool:
        if now is None:
            now = time.monotonic()
        self._refill(now)
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False

    def retry_after(self, cost: float,
                    now: float | None = None) -> float:
        """Seconds until ``cost`` tokens will be available (the
        Retry-After hint)."""
        if now is None:
            now = time.monotonic()
        self._refill(now)
        if self.tokens >= cost:
            return 0.0
        if self.rate <= 0:
            return 60.0
        return (cost - self.tokens) / self.rate


class FrontDoorConfig:
    """Admission knobs.  Defaults are generous enough that existing
    tests and chaos drills never shed; benches and the ``overload``
    nemesis tighten them via env (``from_env``) or explicitly."""

    __slots__ = ("max_conns", "max_inflight", "max_queue_depth",
                 "workers", "tenant_rate", "tenant_burst",
                 "tenant_inflight", "tenant_watches", "write_cost",
                 "read_cost", "tenant_overrides")

    def __init__(self, *, max_conns: int = 100_000,
                 max_inflight: int = 4096,
                 max_queue_depth: int = 8192, workers: int = 16,
                 tenant_rate: float = 5000.0,
                 tenant_burst: float = 10_000.0,
                 tenant_inflight: int = 1024,
                 tenant_watches: int = 200_000,
                 write_cost: float = 1.0, read_cost: float = 0.2,
                 tenant_overrides: dict | None = None):
        self.max_conns = max_conns
        self.max_inflight = max_inflight
        self.max_queue_depth = max_queue_depth
        self.workers = workers
        self.tenant_rate = tenant_rate
        self.tenant_burst = tenant_burst
        self.tenant_inflight = tenant_inflight
        self.tenant_watches = tenant_watches
        self.write_cost = write_cost
        self.read_cost = read_cost
        #: tenant -> (rate, burst, inflight, watches)
        self.tenant_overrides = dict(tenant_overrides or {})

    @classmethod
    def from_env(cls, env) -> "FrontDoorConfig":
        def _num(key, default, conv):
            v = env.get(key)
            if v is None or v == "":
                return default
            try:
                return conv(v)
            except ValueError:
                log.warning("frontdoor: ignoring bad %s=%r", key, v)
                return default

        overrides = {}
        spec = env.get("ETCD_FRONTDOOR_TENANTS", "")
        # name=rate,burst,inflight[,watches];name2=...
        for part in filter(None, spec.split(";")):
            try:
                name, vals = part.split("=", 1)
                nums = vals.split(",")
                rate, burst = float(nums[0]), float(nums[1])
                infl = int(nums[2])
                watches = int(nums[3]) if len(nums) > 3 else None
                overrides[name.strip()] = (rate, burst, infl, watches)
            except (ValueError, IndexError):
                log.warning("frontdoor: bad tenant override %r", part)
        return cls(
            max_conns=_num("ETCD_FRONTDOOR_MAX_CONNS", 100_000, int),
            max_inflight=_num("ETCD_FRONTDOOR_MAX_INFLIGHT", 4096,
                              int),
            max_queue_depth=_num("ETCD_FRONTDOOR_MAX_QUEUE", 8192,
                                 int),
            workers=_num("ETCD_FRONTDOOR_WORKERS", 16, int),
            tenant_rate=_num("ETCD_FRONTDOOR_RATE", 5000.0, float),
            tenant_burst=_num("ETCD_FRONTDOOR_BURST", 10_000.0,
                              float),
            tenant_inflight=_num("ETCD_FRONTDOOR_TENANT_INFLIGHT",
                                 1024, int),
            tenant_watches=_num("ETCD_FRONTDOOR_TENANT_WATCHES",
                                200_000, int),
            write_cost=_num("ETCD_FRONTDOOR_WRITE_COST", 1.0, float),
            read_cost=_num("ETCD_FRONTDOOR_READ_COST", 0.2, float),
            tenant_overrides=overrides,
        )


class _TenantState:
    __slots__ = ("bucket", "inflight", "watches", "max_inflight",
                 "max_watches", "label", "gauge")

    def __init__(self, cfg: FrontDoorConfig, name: str, label: str):
        rate, burst = cfg.tenant_rate, cfg.tenant_burst
        infl, watches = cfg.tenant_inflight, cfg.tenant_watches
        ov = cfg.tenant_overrides.get(name)
        if ov is not None:
            rate, burst, infl = ov[0], ov[1], ov[2]
            if ov[3] is not None:
                watches = ov[3]
        self.bucket = TokenBucket(rate, burst)
        self.inflight = 0
        self.watches = 0
        self.max_inflight = infl
        self.max_watches = watches
        self.label = label
        self.gauge = _obs.registry.gauge("etcd_tenant_inflight",
                                         tenant=label)


#: admission outcomes / reasons (the typed vocabulary the CATALOG
#: families and the 429 cause carry)
ADMIT = "admit"
SHED_WRITE = "shed_write"
SHED_ALL = "shed_all"
CLOSE = "close"


class Admission:
    """Admission policy state: per-tenant buckets/quotas + global
    ceilings.  Loop-thread-only — no locks; the front door calls it
    exclusively from the event loop (single-ownership model)."""

    def __init__(self, cfg: FrontDoorConfig,
                 queue_depth=lambda: 0):
        self.cfg = cfg
        self.inflight = 0
        self.queue_depth = queue_depth
        self.tenants: dict[str, _TenantState] = {}
        #: (outcome, reason) -> count; the local mirror /v2/stats/
        #: frontdoor serves (the registry is the export path)
        self.counts: dict[tuple[str, str], int] = {}

    def _bill(self, outcome: str, reason: str) -> None:
        _admit_counter(outcome, reason).inc()
        k = (outcome, reason)
        self.counts[k] = self.counts.get(k, 0) + 1

    def state(self, tenant: str) -> _TenantState:
        st = self.tenants.get(tenant)
        if st is None:
            if len(self.tenants) >= TENANT_STATE_MAX:
                # tenant-name flood: further tenants share one state
                # (bounded memory beats per-abuser precision)
                st = self.tenants.get("_overflow")
                if st is None:
                    st = _TenantState(self.cfg, "_overflow", "_other")
                    self.tenants["_overflow"] = st
                return st
            label = tenant if len(self.tenants) < TENANT_LABEL_MAX \
                else "_other"
            st = _TenantState(self.cfg, tenant, label)
            self.tenants[tenant] = st
        return st

    def decide(self, tenant: str, is_write: bool,
               now: float | None = None):
        """One admission decision.  Returns ``(outcome, reason,
        retry_after)``; callers must :meth:`begin` iff outcome is
        ADMIT.  Order: global ceilings (cheapest, protect the node)
        → tenant inflight → tenant bucket (write cost > read cost, so
        a draining bucket sheds writes first and reads last — the
        NOSPACE degradation shape, per tenant)."""
        if now is None:
            now = time.monotonic()
        if self.inflight >= self.cfg.max_inflight:
            self._bill(SHED_ALL, "global_inflight")
            return SHED_ALL, "global_inflight", 1.0
        if self.queue_depth() >= self.cfg.max_queue_depth:
            self._bill(SHED_ALL, "queue_depth")
            return SHED_ALL, "queue_depth", 1.0
        st = self.state(tenant)
        if st.inflight >= st.max_inflight:
            self._bill(SHED_ALL, "tenant_inflight")
            return SHED_ALL, "tenant_inflight", 1.0
        cost = self.cfg.write_cost if is_write else self.cfg.read_cost
        if not st.bucket.take(cost, now):
            ra = st.bucket.retry_after(cost, now)
            outcome = SHED_WRITE if is_write else SHED_ALL
            self._bill(outcome, "tenant_rate")
            return outcome, "tenant_rate", ra
        self._bill(ADMIT, "ok")
        return ADMIT, "ok", 0.0

    def begin(self, tenant: str) -> None:
        self.inflight += 1
        st = self.state(tenant)
        st.inflight += 1
        st.gauge.inc()

    def finish(self, tenant: str) -> None:
        self.inflight -= 1
        st = self.state(tenant)
        st.inflight -= 1
        st.gauge.inc(-1)

    def try_add_watches(self, tenant: str, n: int) -> bool:
        st = self.state(tenant)
        if st.watches + n > st.max_watches:
            return False
        st.watches += n
        return True

    def release_watches(self, tenant: str, n: int) -> None:
        st = self.state(tenant)
        st.watches = max(0, st.watches - n)

    def stats(self) -> dict:
        return {
            "inflight": self.inflight,
            "queueDepth": self.queue_depth(),
            "admission": {f"{o}/{r}": n
                          for (o, r), n in sorted(self.counts.items())},
            "tenants": {
                name: {"inflight": st.inflight,
                       "watches": st.watches,
                       "tokens": round(st.bucket.tokens, 3)}
                for name, st in self.tenants.items()
            },
        }


class _ConnSink(WatchMux):
    """A connection's watch delivery sink: a :class:`WatchMux` that
    kicks the event loop when items land.  ``kicked`` (guarded by the
    loop's completions lock) dedupes kicks — one mailbox entry per
    drain, however many events the fanout threads deliver."""

    __slots__ = ("loop", "conn", "kicked")

    def __init__(self, loop: "FrontDoor", conn: "_Conn",
                 capacity: int = 4096):
        super().__init__(capacity=capacity)
        self.loop = loop
        self.conn = conn
        self.kicked = False

    def offer(self, mid, e, block_s=None):
        ok = super().offer(mid, e, block_s)
        if ok:
            self.loop._watch_kick(self)
        return ok

    def offer_closed(self, mid):
        super().offer_closed(mid)
        self.loop._watch_kick(self)


class _Conn:
    """Per-connection state, owned exclusively by the loop thread."""

    __slots__ = ("sock", "fd", "addr", "mode", "rbuf", "out",
                 "close_after", "epoch", "tenant", "origin",
                 "want_write", "sink", "watchers", "open_members",
                 "single", "watch_count", "keepalive", "deadline_at",
                 "last_write", "chunked")

    def __init__(self, sock, addr):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.mode = "idle"  # idle | busy | watch | closed  # owner: frontdoor-loop
        self.rbuf = bytearray()  # owner: frontdoor-loop
        self.out = bytearray()  # owner: frontdoor-loop
        self.close_after = False  # owner: frontdoor-loop
        self.epoch = 0  # owner: frontdoor-loop
        self.tenant = None  # tenant billed for the inflight request  # owner: frontdoor-loop
        self.origin = ""  # owner: frontdoor-loop
        self.want_write = False  # owner: frontdoor-loop
        self.sink: _ConnSink | None = None  # owner: frontdoor-loop
        self.watchers: list | None = None  # owner: frontdoor-loop
        self.open_members = 0  # owner: frontdoor-loop
        self.single = False  # untagged single-watch line format  # owner: frontdoor-loop
        self.watch_count = 0  # quota units to release at teardown  # owner: frontdoor-loop
        self.keepalive = 0.0  # owner: frontdoor-loop
        self.deadline_at = 0.0  # owner: frontdoor-loop
        self.last_write = 0.0  # owner: frontdoor-loop
        self.chunked = False  # owner: frontdoor-loop


def _status_line(status: int) -> bytes:
    phrases = {200: "OK", 201: "Created", 204: "No Content",
               400: "Bad Request", 403: "Forbidden",
               404: "Not Found", 405: "Method Not Allowed",
               412: "Precondition Failed", 413: "Payload Too Large",
               429: "Too Many Requests",
               431: "Request Header Fields Too Large",
               500: "Internal Server Error",
               503: "Service Unavailable",
               507: "Insufficient Storage"}
    return (f"HTTP/1.1 {status} "
            f"{phrases.get(status, 'Unknown')}\r\n").encode()


def _response(status: int, body: bytes, headers: dict | None = None,
              close: bool = False) -> bytes:
    out = bytearray(_status_line(status))
    for k, v in (headers or {}).items():
        out += f"{k}: {v}\r\n".encode()
    out += f"Content-Length: {len(body)}\r\n".encode()
    if close:
        out += b"Connection: close\r\n"
    out += b"\r\n"
    out += body
    return bytes(out)


def _error_parts(err: Exception) -> tuple[int, dict, bytes]:
    """``(status, headers, body)`` for an error — assembled into a
    response on the loop thread (via ``_reply``) so CORS headers get
    injected there, same as every other reply."""
    if isinstance(err, EtcdError):
        body = (err.to_json() + "\n").encode()
        headers = {"Content-Type": "application/json",
                   "X-Etcd-Index": str(err.index)}
        if isinstance(err, EtcdOverCapacity):
            # integer-second ceiling, minimum 1: Retry-After is a
            # pacing hint, and "0" invites an immediate retry storm
            headers["Retry-After"] = str(max(
                1, int(err.retry_after + 0.999)))
        return err.http_status(), headers, body
    log.warning("frontdoor: internal error: %s", err)
    return 500, {}, b"Internal Server Error\n"


class FrontDoor:
    """Selectors-based client front end for one listener.

    Exposes the ``_Server`` surface cli.py relies on
    (``server_address``, ``shutdown()``) so the two serving modes are
    interchangeable."""

    def __init__(self, etcd, host: str, port: int, *,
                 config: FrontDoorConfig | None = None,
                 cors: set[str] | None = None,
                 server_timeout: float | None = None,
                 watch_timeout: float | None = None,
                 watch_keepalive: float | None = None,
                 extra_routes: dict | None = None,
                 watch_redirect: str | None = None):
        # lazy: api.http imports LISTEN_BACKLOG from this module at
        # module level, so the reverse import must happen at runtime
        from ..api import http as _http

        self._http = _http
        self.etcd = etcd
        self.cfg = config or FrontDoorConfig()
        self.cors = cors
        self.server_timeout = (_http.DEFAULT_SERVER_TIMEOUT
                               if server_timeout is None
                               else server_timeout)
        self.watch_timeout = (_http.DEFAULT_WATCH_TIMEOUT
                              if watch_timeout is None
                              else watch_timeout)
        self.watch_keepalive = (_http.DEFAULT_WATCH_KEEPALIVE
                                if watch_keepalive is None
                                else watch_keepalive)
        # role-split hooks (PR 15).  extra_routes: exact path ->
        # handler(method, path, query, headers, body) returning
        # (status, headers, body); runs on the worker pool so batch
        # endpoints (the ingest role's /mraft/propose_many lineage)
        # never stall the event loop.  watch_redirect: base URL of
        # the apply/watch worker — wait= requests 307 there, keeping
        # the stateless ingest free of long-held watch connections.
        self.extra_routes = extra_routes or {}
        self.watch_redirect = watch_redirect

        self._lsock = socket.socket(socket.AF_INET,
                                    socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET,
                               socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(LISTEN_BACKLOG)
        self._lsock.setblocking(False)

        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)

        self._conns: dict[int, _Conn] = {}  # owner: frontdoor-loop
        # bounded handoff to the worker pool; depth is an admission
        # input (queue_depth ceiling), so overload surfaces as a 429
        # at the door, not latency inside
        self._jobs: queue.Queue = queue.Queue(
            maxsize=self.cfg.max_queue_depth)
        self.admission = Admission(self.cfg, self._jobs.qsize)

        self._lock = threading.Lock()
        self._completions: list = []
        self._wake_armed = False

        self._timers: list = []  # owner: frontdoor-loop
        self._tseq = 0  # owner: frontdoor-loop
        self._stopping = False
        self._threads: list[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    @property
    def server_address(self):
        return self._lsock.getsockname()

    def start(self) -> "FrontDoor":
        self._sel.register(self._lsock, selectors.EVENT_READ,
                           "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           "wakeup")
        t = threading.Thread(target=self._run, daemon=True,
                             name="frontdoor-loop")
        t.start()
        self._threads.append(t)
        for i in range(self.cfg.workers):
            w = threading.Thread(target=self._worker, daemon=True,
                                 name=f"frontdoor-worker-{i}")
            w.start()
            self._threads.append(w)
        return self

    def shutdown(self) -> None:
        self._stopping = True
        self._wake()
        # best-effort fast wakeup; a full queue may drop sentinels,
        # in which case workers still exit via the _stopping flag
        # within their get() timeout
        for _ in range(self.cfg.workers):
            try:
                self._jobs.put_nowait(None)
            except queue.Full:
                break
        for t in self._threads:
            t.join(timeout=2.0)

    def stats_json(self) -> bytes:
        s = self.admission.stats()
        s["connsOpen"] = len(self._conns)
        return (json.dumps(s) + "\n").encode()

    # -- cross-thread mailbox ----------------------------------------------

    def _wake(self) -> None:
        with self._lock:
            if self._wake_armed:
                return
            self._wake_armed = True
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass

    def _post(self, item) -> None:
        with self._lock:
            self._completions.append(item)
        self._wake()

    def _watch_kick(self, sink: _ConnSink) -> None:
        with self._lock:
            if sink.kicked:
                return
            sink.kicked = True
            self._completions.append(("watch", sink.conn))
        self._wake()

    # -- event loop --------------------------------------------------------

    def _run(self) -> None:
        while not self._stopping:
            timeout = self._timer_delay()
            for key, _mask in self._sel.select(timeout):
                try:
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wakeup":
                        self._drain_wakeup()
                    else:
                        conn = key.data
                        if _mask_writable(_mask):
                            self._flush(conn)
                        if conn.mode != "closed" \
                                and _mask_readable(_mask):
                            self._on_readable(conn)
                except Exception:  # the loop must never die
                    log.exception("frontdoor: event handler error")
                    if isinstance(key.data, _Conn):
                        self._teardown(key.data)
            try:
                self._fire_timers()
                self._process_completions()
            except Exception:  # pragma: no cover
                log.exception("frontdoor: loop maintenance error")
        # teardown
        for conn in list(self._conns.values()):
            self._teardown(conn)
        try:
            self._sel.unregister(self._lsock)
        except KeyError:
            pass
        self._lsock.close()
        self._sel.close()
        self._wake_r.close()
        self._wake_w.close()

    def _drain_wakeup(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass
        with self._lock:
            self._wake_armed = False

    def _process_completions(self) -> None:
        while True:
            with self._lock:
                if not self._completions:
                    return
                batch = self._completions
                self._completions = []
            for item in batch:
                kind = item[0]
                if kind == "resp":
                    _k, conn, epoch, parts, close = item
                    if conn.epoch != epoch or conn.mode != "busy":
                        continue  # conn was torn down meanwhile
                    if conn.tenant is not None:
                        self.admission.finish(conn.tenant)
                        conn.tenant = None
                    conn.mode = "idle"
                    conn.close_after = conn.close_after or close
                    status, headers, body = parts
                    self._reply(conn, status, body, headers)
                    if conn.mode != "closed" \
                            and not conn.close_after:
                        self._process_rbuf(conn)
                elif kind == "watch":
                    _k, conn = item
                    with self._lock:
                        if conn.sink is not None:
                            conn.sink.kicked = False
                    if conn.mode == "watch":
                        self._drain_watch(conn)

    # -- timers ------------------------------------------------------------

    def _arm(self, when: float, kind: str, conn: _Conn) -> None:
        self._tseq += 1
        heapq.heappush(self._timers,
                       (when, self._tseq, kind, conn, conn.epoch))

    def _timer_delay(self) -> float:
        if not self._timers:
            return 0.5
        delay = self._timers[0][0] - time.monotonic()
        return min(0.5, max(0.0, delay))

    def _fire_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _when, _seq, kind, conn, epoch = heapq.heappop(
                self._timers)
            if conn.epoch != epoch or conn.mode != "watch":
                continue  # stale timer (lazy invalidation)
            if kind == "deadline":
                self._end_watch(conn)
            elif kind == "ka":
                if conn.keepalive and \
                        now - conn.last_write >= conn.keepalive:
                    self._queue_chunk(conn, b"\n")
                self._arm(now + (conn.keepalive or 1.0), "ka", conn)

    # -- accept / read / write ---------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            try:
                act = _faults.hit("frontdoor.accept")
                if act == _faults.DROP:
                    sock.close()
                    continue
            except OSError:
                sock.close()
                continue
            if len(self._conns) >= self.cfg.max_conns:
                # connection ceiling: close before a byte is read —
                # the one decision that cannot be a 429 (parsing the
                # request would cost the memory the ceiling protects)
                self.admission._bill(CLOSE, "conn_ceiling")
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr)
            self._conns[conn.fd] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            _M_CONNS.inc()

    def _on_readable(self, conn: _Conn) -> None:
        try:
            act = _faults.hit("frontdoor.read")
            if act == _faults.DROP:
                self._teardown(conn)
                return
        except OSError:
            self._queue_bytes(conn, _response(
                503, b"injected fault\n", None, True))
            conn.close_after = True
            return
        got = 0
        while got < READ_QUANTUM:
            try:
                data = conn.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._teardown(conn)
                return
            if not data:
                self._teardown(conn)
                return
            conn.rbuf += data
            got += len(data)
            if len(data) < 65536:
                break
        if len(conn.rbuf) > MAX_HEADER_BYTES + MAX_BODY_BYTES:
            self._teardown(conn)
            return
        if conn.mode == "idle":
            self._process_rbuf(conn)

    def _queue_bytes(self, conn: _Conn, data: bytes) -> None:
        conn.out += data
        conn.last_write = time.monotonic()
        self._flush(conn)

    def _queue_chunk(self, conn: _Conn, data: bytes) -> None:
        self._queue_bytes(conn, f"{len(data):x}\r\n".encode()
                          + data + b"\r\n")

    def _flush(self, conn: _Conn) -> None:
        if conn.mode == "closed":
            return
        while conn.out:
            try:
                n = conn.sock.send(conn.out)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._teardown(conn)
                return
            if n == 0:
                break
            del conn.out[:n]
        if len(conn.out) > MAX_OUT_BYTES:
            # slow consumer: evict rather than buffer without bound
            self._teardown(conn)
            return
        want = bool(conn.out)
        if want != conn.want_write:
            conn.want_write = want
            events = selectors.EVENT_READ
            if want:
                events |= selectors.EVENT_WRITE
            try:
                self._sel.modify(conn.sock, events, conn)
            except (KeyError, ValueError, OSError):
                pass
        if not conn.out and conn.close_after \
                and conn.mode in ("idle",):
            self._teardown(conn)

    def _teardown(self, conn: _Conn) -> None:
        if conn.mode == "closed":
            return
        if conn.mode == "busy" and conn.tenant is not None:
            self.admission.finish(conn.tenant)
            conn.tenant = None
        if conn.sink is not None:
            self._close_watch_state(conn)
        conn.mode = "closed"
        conn.epoch += 1
        self._conns.pop(conn.fd, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        _M_CONNS.inc(-1)

    # -- request parsing ---------------------------------------------------

    def _process_rbuf(self, conn: _Conn) -> None:
        while conn.mode == "idle" and not conn.close_after:
            end = conn.rbuf.find(b"\r\n\r\n")
            if end < 0:
                if len(conn.rbuf) > MAX_HEADER_BYTES:
                    self._queue_bytes(conn, _response(
                        431, b"header too large\n", None, True))
                    conn.close_after = True
                return
            head = bytes(conn.rbuf[:end])
            try:
                lines = head.decode("latin-1").split("\r\n")
                method, target, version = lines[0].split(" ", 2)
                headers = {}
                for ln in lines[1:]:
                    k, _, v = ln.partition(":")
                    headers[k.strip().lower()] = v.strip()
            except (ValueError, IndexError):
                self._queue_bytes(conn, _response(
                    400, b"bad request\n", None, True))
                conn.close_after = True
                return
            try:
                clen = int(headers.get("content-length") or 0)
            except ValueError:
                clen = 0
            if clen > MAX_BODY_BYTES:
                self._queue_bytes(conn, _response(
                    413, b"body too large\n", None, True))
                conn.close_after = True
                return
            total = end + 4 + clen
            if len(conn.rbuf) < total:
                return  # body still in flight
            body = bytes(conn.rbuf[end + 4:total])
            del conn.rbuf[:total]
            connhdr = headers.get("connection", "").lower()
            if connhdr == "close" or (version == "HTTP/1.0"
                                      and connhdr != "keep-alive"):
                conn.close_after = True
            conn.origin = headers.get("origin", "")
            self._dispatch(conn, method, target, headers, body)

    def _cors_headers(self, conn: _Conn) -> dict:
        if not self.cors:
            return {}
        if "*" in self.cors:
            allow = "*"
        elif conn.origin in self.cors:
            allow = conn.origin
        else:
            return {}
        return {
            "Access-Control-Allow-Methods":
                "POST, GET, OPTIONS, PUT, DELETE",
            "Access-Control-Allow-Origin": allow,
            "Access-Control-Allow-Headers": "accept, content-type",
        }

    def _reply(self, conn: _Conn, status: int, body: bytes,
               headers: dict | None = None) -> None:
        h = dict(headers or {})
        h.update(self._cors_headers(conn))
        self._queue_bytes(conn, _response(status, body, h,
                                          conn.close_after))

    def _reply_error(self, conn: _Conn, err: Exception) -> None:
        status, h, body = _error_parts(err)
        self._reply(conn, status, body, h)

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, conn: _Conn, method: str, target: str,
                  headers: dict, body: bytes) -> None:
        _http = self._http
        parsed = urllib.parse.urlsplit(target)
        path = urllib.parse.unquote(parsed.path)

        if method == "OPTIONS":
            if self.cors:
                self._reply(conn, 200, b"")
            else:
                self._reply(conn, 405, b"Method Not Allowed\n",
                            {"Allow": "GET,PUT,POST,DELETE"})
            return
        if method not in ("GET", "PUT", "POST", "DELETE", "HEAD"):
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "GET,PUT,POST,DELETE"})
            return

        # ops plane: inline, admission-exempt — an overloaded node
        # must stay observable
        if path == _http.METRICS_PREFIX:
            self._serve_metrics(conn, method)
            return
        if path.startswith(_http.STATS_PREFIX):
            self._serve_stats(conn, method, path)
            return
        if path == _http.MACHINES_PREFIX:
            self._serve_machines(conn, method)
            return

        handler = self.extra_routes.get(path)
        if handler is not None:
            conn.mode = "busy"
            try:
                self._jobs.put_nowait(
                    (conn, conn.epoch,
                     ("route", handler, method, path, parsed.query,
                      headers, body)))
            except queue.Full:
                conn.mode = "idle"
                self._reply(conn, 503, b"overloaded\n",
                            {"Retry-After": "1"})
            return

        if path == _http.WATCH_PREFIX:
            if self.watch_redirect is not None:
                self._redirect_watch(conn, _http.WATCH_PREFIX, "")
                return
            self._serve_watch_many(conn, method, headers, body)
            return
        if path.startswith(_http.KEYS_PREFIX):
            self._serve_keys(conn, method, path, parsed.query,
                             headers, body)
            return
        self._reply(conn, 404, b"404 page not found\n")

    def _form(self, query: str, headers: dict,
              body: bytes) -> dict:
        form = urllib.parse.parse_qs(query, keep_blank_values=True)
        if body:
            ctype = headers.get("content-type", "")
            if "application/x-www-form-urlencoded" in ctype \
                    or not ctype:
                body_form = urllib.parse.parse_qs(
                    body.decode(), keep_blank_values=True)
                for k, v in form.items():
                    body_form.setdefault(k, v)
                form = body_form
        return form

    def _serve_keys(self, conn: _Conn, method: str, path: str,
                    query: str, headers: dict, body: bytes) -> None:
        if method not in ("GET", "PUT", "POST", "DELETE"):
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "GET,PUT,POST,DELETE"})
            return
        try:
            form = self._form(query, headers, body)
            rr = self._http.parse_request(method, path, form,
                                          gen_id())
            keepalive = self.watch_keepalive
            if "keepalive" in form:
                try:
                    keepalive = float(form["keepalive"][0])
                    # non-finite values poison the timer heap (a NaN
                    # at the top can never be popped)
                    if keepalive < 0 or not math.isfinite(keepalive):
                        raise ValueError
                except ValueError:
                    raise EtcdError(
                        ECODE_INVALID_FIELD,
                        'invalid value for "keepalive"') from None
        except EtcdError as e:
            self._reply_error(conn, e)
            return
        except UnicodeDecodeError:
            self._reply(conn, 400, b"bad request\n")
            return

        tenant = parse_tenant(headers, path)
        is_write = method != "GET"
        outcome, reason, ra = self.admission.decide(tenant, is_write)
        if outcome != ADMIT:
            self._reply_error(conn, EtcdOverCapacity(
                cause=f"{tenant}: {reason}",
                index=self.etcd.store.index(), retry_after=ra))
            return

        if rr.wait:
            if self.watch_redirect is not None:
                self._redirect_watch(conn, path, query)
                return
            self._start_single_watch(conn, rr, tenant, keepalive)
            return

        self.admission.begin(tenant)
        conn.tenant = tenant
        conn.mode = "busy"
        try:
            self._jobs.put_nowait((conn, conn.epoch, rr))
        except queue.Full:
            # decide() raced a fill-up; shed honestly
            self.admission.finish(tenant)
            conn.tenant = None
            conn.mode = "idle"
            self.admission._bill(SHED_ALL, "queue_depth")
            self._reply_error(conn, EtcdOverCapacity(
                cause=f"{tenant}: queue_depth",
                index=self.etcd.store.index(), retry_after=1.0))

    def _redirect_watch(self, conn: _Conn, path: str,
                        query: str) -> None:
        """307 to the watch worker: method + body survive the hop,
        and stock HTTP clients re-issue wait GETs transparently."""
        loc = self.watch_redirect + path + (f"?{query}" if query
                                            else "")
        self._reply(conn, 307, b"", {"Location": loc})

    # -- worker pool -------------------------------------------------------

    def _worker(self) -> None:
        # _stopping is the authoritative exit signal: the None
        # sentinels shutdown() queues are best-effort wakeups that a
        # full job queue may never deliver
        while not self._stopping:
            try:
                job = self._jobs.get(timeout=0.5)
            except queue.Empty:
                continue
            if job is None:
                return
            conn, epoch, rr = job
            try:
                if type(rr) is tuple and rr[0] == "route":
                    _tag, handler, method, path, query, headers, \
                        body = rr
                    parts = handler(method, path, query, headers,
                                    body)
                else:
                    parts = self._do_request(rr)
            except Exception as e:  # pragma: no cover
                log.exception("frontdoor: worker error")
                parts = _error_parts(e)
            self._post(("resp", conn, epoch, parts, False))

    def _do_request(self, rr) -> tuple[int, dict, bytes]:
        """``(status, headers, body)`` — the loop thread assembles
        the wire response (and adds CORS headers) in the ``resp``
        completion handler."""
        try:
            resp = self.etcd.do(rr, timeout=self.server_timeout)
        except EtcdError as e:
            return _error_parts(e)
        except TimeoutError:
            return _error_parts(EtcdError(
                ECODE_RAFT_INTERNAL, "request timed out"))
        ev = resp.event
        if ev is None:  # pragma: no cover
            return _error_parts(
                RuntimeError("no event in response"))
        body = (json.dumps(ev.to_dict()) + "\n").encode()
        status = 201 if ev.is_created() else 200
        return status, {
            "Content-Type": "application/json",
            "X-Etcd-Index": str(ev.etcd_index),
            "X-Raft-Index": str(self.etcd.index()),
            "X-Raft-Term": str(self.etcd.term()),
        }, body

    # -- watch serving (threadless) ----------------------------------------

    def _watch_headers(self, conn: _Conn, etcd_index: int) -> None:
        out = bytearray(_status_line(200))
        out += b"Content-Type: application/json\r\n"
        out += f"X-Etcd-Index: {etcd_index}\r\n".encode()
        out += f"X-Raft-Index: {self.etcd.index()}\r\n".encode()
        out += f"X-Raft-Term: {self.etcd.term()}\r\n".encode()
        out += b"Transfer-Encoding: chunked\r\n"
        for k, v in self._cors_headers(conn).items():
            out += f"{k}: {v}\r\n".encode()
        out += b"\r\n"
        self._queue_bytes(conn, bytes(out))
        conn.chunked = True

    def _begin_watch(self, conn: _Conn, tenant: str, single: bool,
                     keepalive: float) -> None:
        conn.mode = "watch"
        conn.tenant = tenant
        conn.single = single
        conn.keepalive = keepalive
        conn.last_write = time.monotonic()
        conn.deadline_at = time.monotonic() + self.watch_timeout
        self._arm(conn.deadline_at, "deadline", conn)
        if keepalive:
            self._arm(time.monotonic() + keepalive, "ka", conn)

    def _start_single_watch(self, conn: _Conn, rr, tenant: str,
                            keepalive: float) -> None:
        if not self.admission.try_add_watches(tenant, 1):
            self.admission._bill(SHED_ALL, "tenant_watches")
            self._reply_error(conn, EtcdOverCapacity(
                cause=f"{tenant}: watch quota exhausted",
                index=self.etcd.store.index(), retry_after=1.0))
            return
        sink = _ConnSink(self, conn, capacity=256)
        ws = self.etcd.store.watch_many(
            [(rr.path, rr.recursive, rr.stream, rr.since)],
            mux=sink, mid_base=0)
        w = ws[0]
        if isinstance(w, EtcdError):
            sink.close()
            self.admission.release_watches(tenant, 1)
            self._reply_error(conn, w)
            return
        conn.sink = sink
        conn.watchers = ws
        conn.open_members = 1
        conn.watch_count = 1
        # enter watch mode BEFORE the first write: _flush tears an
        # idle conn down the moment close_after's bytes drain
        self._begin_watch(conn, tenant, single=True,
                          keepalive=(keepalive if rr.stream else 0.0))
        self._watch_headers(conn, w.start_index)
        if w.replay is not None:
            self._replay_member(conn, w, 0,
                                (rr.path, rr.recursive))
        self._drain_watch(conn)

    def _serve_watch_many(self, conn: _Conn, method: str,
                          headers: dict, body: bytes) -> None:
        _http = self._http
        if method != "POST":
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "POST"})
            return
        try:
            doc = json.loads(body or b"[]")
            if not isinstance(doc, list) \
                    or len(doc) > _http.WATCH_BATCH_MAX:
                raise ValueError("bad batch")
            specs = [(str(d.get("key", "/")),
                      bool(d.get("recursive", False)),
                      bool(d.get("stream", True)),
                      int(d.get("since", 0)))
                     for d in doc]
        except (ValueError, TypeError, AttributeError,
                json.JSONDecodeError):
            self._reply_error(conn, EtcdError(
                ECODE_INVALID_FORM,
                "watch batch must be a JSON array of watch specs "
                f"(max {_http.WATCH_BATCH_MAX})"))
            return

        tenant = parse_tenant(headers, "")
        outcome, reason, ra = self.admission.decide(tenant, False)
        if outcome != ADMIT:
            self._reply_error(conn, EtcdOverCapacity(
                cause=f"{tenant}: {reason}",
                index=self.etcd.store.index(), retry_after=ra))
            return
        # the whole batch is checked against the tenant's watch quota
        # AT REGISTRATION — a quota breach is a typed 429 before the
        # stream opens, never a mid-stream eviction
        if not self.admission.try_add_watches(tenant, len(specs)):
            self.admission._bill(SHED_ALL, "tenant_watches")
            self._reply_error(conn, EtcdOverCapacity(
                cause=f"{tenant}: watch quota exhausted "
                      f"({len(specs)} requested)",
                index=self.etcd.store.index(), retry_after=1.0))
            return

        sink = _ConnSink(self, conn, capacity=max(
            4096, 2 * _http.WATCH_REG_CHUNK))
        conn.sink = sink
        conn.watchers = []
        conn.open_members = 0
        conn.watch_count = len(specs)
        # watch mode first, then the first write (see
        # _start_single_watch)
        self._begin_watch(conn, tenant, single=False,
                          keepalive=self.watch_keepalive)
        self._watch_headers(conn, self.etcd.store.index())

        for base in range(0, len(specs), _http.WATCH_REG_CHUNK):
            ws = self.etcd.store.watch_many(
                specs[base:base + _http.WATCH_REG_CHUNK], mux=sink,
                mid_base=base)
            conn.watchers.extend(ws)
            for i, w in enumerate(ws, start=base):
                if isinstance(w, EtcdError):
                    self._queue_chunk(conn, (json.dumps(
                        {"watch": i,
                         "error": json.loads(w.to_json())})
                        + "\n").encode())
                else:
                    conn.open_members += 1
            for j, w in enumerate(ws):
                if getattr(w, "replay", None) is not None:
                    self._replay_member(conn, w, base + j,
                                        specs[base + j])
            if conn.mode != "watch":
                return  # slow-consumer eviction mid-registration
            self._drain_watch(conn, end_ok=False)
        self._drain_watch(conn)

    def _replay_member(self, conn: _Conn, w, mid: int,
                       spec) -> None:
        """History catch-up ``[w.replay, w.since_index)`` straight to
        the wire (same contract as api/http.py's replay: live
        dispatch neither overlaps nor gaps it)."""
        key = clean_path(spec[0])
        recursive = spec[1]
        eh = self.etcd.store.watcher_hub.event_history
        nxt = w.replay
        while nxt < w.since_index and conn.mode != "closed":
            try:
                ev = eh.scan(key, recursive, nxt)
            except EtcdError as err:
                if not conn.single:
                    self._queue_chunk(conn, (json.dumps(
                        {"watch": mid,
                         "error": json.loads(err.to_json())})
                        + "\n").encode())
                w.remove()  # closed marker arrives via the sink
                return
            if ev is None or ev.index() >= w.since_index:
                return
            if conn.single:
                line = ev.to_dict()
            else:
                line = {"watch": mid}
                line.update(ev.to_dict())
            self._queue_chunk(conn, (json.dumps(line)
                                     + "\n").encode())
            nxt = ev.index() + 1

    def _drain_watch(self, conn: _Conn, end_ok: bool = True) -> None:
        sink = conn.sink
        if sink is None or conn.mode != "watch":
            return
        got_event = False
        while True:
            item = sink.pop(timeout=0)
            if item is None:
                break
            mid, ev = item
            if ev is None:
                conn.open_members -= 1
                if not conn.single:
                    self._queue_chunk(conn, (json.dumps(
                        {"watch": mid, "closed": True})
                        + "\n").encode())
                continue
            if conn.single:
                line = ev.to_dict()
            else:
                line = {"watch": mid}
                line.update(ev.to_dict())
            self._queue_chunk(conn, (json.dumps(line)
                                     + "\n").encode())
            got_event = True
            if conn.mode != "watch":
                return  # evicted while writing
        if conn.single and got_event and conn.watchers \
                and not getattr(conn.watchers[0], "stream", True):
            # one-shot long-poll: first event ends the exchange
            self._end_watch(conn)
            return
        if end_ok and conn.open_members <= 0:
            self._end_watch(conn)

    def _close_watch_state(self, conn: _Conn) -> None:
        """Release watch resources: sink FIRST so the batched
        removal's member closes are no-ops, then hub removal, then
        the quota."""
        sink, watchers = conn.sink, conn.watchers
        conn.sink = None
        conn.watchers = None
        if sink is not None:
            sink.close()
        if watchers:
            self.etcd.store.watcher_hub.remove_many(watchers)
        if conn.watch_count and conn.tenant is not None:
            self.admission.release_watches(conn.tenant,
                                           conn.watch_count)
        conn.watch_count = 0
        conn.tenant = None
        conn.open_members = 0

    def _end_watch(self, conn: _Conn) -> None:
        if conn.mode != "watch":
            return
        self._close_watch_state(conn)
        self._queue_chunk(conn, b"")  # terminating chunk
        conn.chunked = False
        conn.single = False
        conn.mode = "idle"
        if conn.mode == "idle" and not conn.close_after:
            self._process_rbuf(conn)
        elif conn.close_after and not conn.out:
            self._teardown(conn)

    # -- ops plane ---------------------------------------------------------

    def _serve_metrics(self, conn: _Conn, method: str) -> None:
        if method != "GET":
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "GET"})
            return
        from ..obs.exporter import CONTENT_TYPE, render_prometheus

        self._reply(conn, 200, render_prometheus(_obs.registry),
                    {"Content-Type": CONTENT_TYPE})

    def _serve_stats(self, conn: _Conn, method: str,
                     path: str) -> None:
        if method != "GET":
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "GET"})
            return
        sub = path[len(self._http.STATS_PREFIX):].strip("/")
        if sub == "store":
            body = self.etcd.store.json_stats()
        elif sub == "self":
            body = self.etcd.server_stats.to_json()
        elif sub == "leader":
            body = self.etcd.leader_stats.to_json()
        elif sub == "spans":
            from ..utils.trace import tracer

            body = tracer.snapshot_json()
        elif sub == "slo":
            # declared-objective burn-rate verdict over the
            # windowed-delta ring (PR 17 SLO layer)
            from ..obs import slo as _slo

            body = _slo.default_verdict_json()
        elif sub == "timeseries":
            from ..obs import timeseries as _timeseries

            body = _timeseries.start_default().snapshot_json()
        elif sub == "frontdoor":
            body = self.stats_json()
        else:
            self._reply(conn, 404, b"404 page not found\n")
            return
        self._reply(conn, 200, body,
                    {"Content-Type": "application/json"})

    def _serve_machines(self, conn: _Conn, method: str) -> None:
        if method not in ("GET", "HEAD"):
            self._reply(conn, 405, b"Method Not Allowed\n",
                        {"Allow": "GET,HEAD"})
            return
        endpoints = self.etcd.cluster_store.get().client_urls_all()
        body = ", ".join(endpoints).encode()
        if method == "HEAD":
            h = bytearray(_status_line(200))
            for k, v in self._cors_headers(conn).items():
                h += f"{k}: {v}\r\n".encode()
            h += f"Content-Length: {len(body)}\r\n\r\n".encode()
            self._queue_bytes(conn, bytes(h))
            return
        self._reply(conn, 200, body)


def _mask_readable(mask: int) -> bool:
    return bool(mask & selectors.EVENT_READ)


def _mask_writable(mask: int) -> bool:
    return bool(mask & selectors.EVENT_WRITE)


def serve_frontdoor(etcd, host: str, port: int, ssl_context=None,
                    cors: set[str] | None = None,
                    config: FrontDoorConfig | None = None, **kw):
    """Start the event-driven front door on ``host:port``; returns an
    object with the ``_Server`` surface (``server_address``,
    ``shutdown()``).

    TLS listeners fall back to the threaded server: a non-blocking
    TLS handshake state machine is out of scope here, and the
    admission-relevant deployments terminate TLS in front."""
    if ssl_context is not None:
        from ..api import http as _http

        log.info("frontdoor: TLS listener falls back to the "
                 "threaded server")
        return _http.serve(_http.make_client_handler(etcd, cors=cors,
                                                     **kw),
                           host, port, ssl_context)
    fd = FrontDoor(etcd, host, port,
                   config=config or FrontDoorConfig.from_env(
                       os.environ),
                   cors=cors, **kw)
    return fd.start()
