"""Cluster membership (reference etcdserver/cluster.go, member.go,
cluster_store.go).

Member identity is sha1(name + peerURLs) truncated to uint64
(member.go:37-55).  Runtime membership is replicated *inside the KV
store* under /_etcd/machines/<hex-id>, so conf changes ride the same
consensus log as user writes.
"""

from __future__ import annotations

import hashlib
import json
import random
import urllib.parse

from ..store import PERMANENT, Store
from ..utils.errors import ECODE_KEY_NOT_FOUND, EtcdError

MACHINE_KV_PREFIX = "/_etcd/machines/"
RAFT_ATTRIBUTES_SUFFIX = "/raftAttributes"
ATTRIBUTES_SUFFIX = "/attributes"
RAFT_PREFIX = "/raft"


class RaftAttributes:
    def __init__(self, peer_urls: list[str] | None = None):
        self.peer_urls = peer_urls or []

    def to_dict(self):
        return {"PeerURLs": self.peer_urls}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("PeerURLs") or [])


class Attributes:
    def __init__(self, name: str = "", client_urls: list[str] | None = None):
        self.name = name
        self.client_urls = client_urls or []

    def to_dict(self):
        return {"Name": self.name, "ClientURLs": self.client_urls}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("Name", ""), d.get("ClientURLs") or [])


class Member:
    def __init__(self, id: int = 0, name: str = "",
                 peer_urls: list[str] | None = None,
                 client_urls: list[str] | None = None):
        self.id = id
        self.raft_attributes = RaftAttributes(peer_urls)
        self.attributes = Attributes(name, client_urls)

    @property
    def name(self) -> str:
        return self.attributes.name

    @property
    def peer_urls(self) -> list[str]:
        return self.raft_attributes.peer_urls

    @property
    def client_urls(self) -> list[str]:
        return self.attributes.client_urls

    def store_key(self) -> str:
        return MACHINE_KV_PREFIX + format(self.id, "x")

    def to_dict(self) -> dict:
        d = {"ID": self.id}
        d.update(self.raft_attributes.to_dict())
        d.update(self.attributes.to_dict())
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Member":
        return cls(id=d.get("ID", 0), name=d.get("Name", ""),
                   peer_urls=d.get("PeerURLs") or [],
                   client_urls=d.get("ClientURLs") or [])

    def __repr__(self):
        return f"Member(id={self.id:x}, name={self.name!r}, " \
               f"peers={self.peer_urls})"


def new_member(name: str, peer_urls: list[str],
               now: float | None = None) -> Member:
    """Generate the deterministic ID from name+peerURLs
    (reference member.go:37-55)."""
    b = name.encode()
    for p in peer_urls:
        b += p.encode()
    if now is not None:
        b += str(int(now)).encode()
    digest = hashlib.sha1(b).digest()
    id = int.from_bytes(digest[:8], "big")
    return Member(id=id, name=name, peer_urls=list(peer_urls))


def parse_member_id(key: str) -> int:
    return int(key.rsplit("/", 1)[-1], 16)


class Cluster(dict):
    """id -> Member map (reference cluster.go:15-128)."""

    def find_id(self, id: int) -> Member | None:
        return self.get(id)

    def find_name(self, name: str) -> Member | None:
        for m in self.values():
            if m.name == name:
                return m
        return None

    def add(self, m: Member) -> None:
        if self.find_id(m.id) is not None:
            raise ValueError(f"member exists with identical ID {m!r}")
        self[m.id] = m

    def pick(self, id: int) -> str:
        """Random peer address for a member (cluster.go:52-63)."""
        m = self.find_id(id)
        if m is None or not m.peer_urls:
            return ""
        return random.choice(m.peer_urls)

    def set_from_string(self, s: str) -> None:
        """Parse 'name1=http://...,name2=http://...'
        (reference cluster.go:66-85)."""
        self.clear()
        # keep_blank_values so "name=" surfaces to the empty-URL
        # guard below instead of silently parsing to an empty cluster
        v = urllib.parse.parse_qs(s.replace(",", "&"),
                                  strict_parsing=False,
                                  keep_blank_values=True)
        for name, urls in v.items():
            if not urls or any(u == "" for u in urls):
                raise ValueError(f"empty URL given for {name!r}")
            m = new_member(name, sorted(urls))
            self.add(m)

    def __str__(self) -> str:
        sl = []
        for m in self.values():
            for u in m.peer_urls:
                sl.append(f"{m.name}={u}")
        return ",".join(sorted(sl))

    def ids(self) -> list[int]:
        return sorted(self.keys())

    def peer_urls_all(self) -> list[str]:
        out = []
        for m in self.values():
            out.extend(m.peer_urls)
        return sorted(out)

    def client_urls_all(self) -> list[str]:
        out = []
        for m in self.values():
            out.extend(m.client_urls)
        return sorted(out)


class ClusterStore:
    """Membership replicated in the KV store
    (reference cluster_store.go:28-104)."""

    def __init__(self, st: Store):
        self.store = st

    def add(self, m: Member) -> None:
        self.store.create(m.store_key() + RAFT_ATTRIBUTES_SUFFIX, False,
                          json.dumps(m.raft_attributes.to_dict()), False,
                          PERMANENT)
        self.store.create(m.store_key() + ATTRIBUTES_SUFFIX, False,
                          json.dumps(m.attributes.to_dict()), False,
                          PERMANENT)

    def get(self) -> Cluster:
        c = Cluster()
        try:
            e = self.store.get(MACHINE_KV_PREFIX, True, True)
        except EtcdError as err:
            if err.error_code == ECODE_KEY_NOT_FOUND:
                return c
            raise
        for n in e.node.nodes or []:
            if len(n.nodes or []) != 2:
                # half-published member (its two attribute keys
                # commit as separate replicated writes): skip until
                # the second lands rather than 500 the reader.
                # ONLY the structural case is skipped — a corrupt
                # value (json error) must still surface, not be
                # silently indistinguishable from mid-publish
                continue
            c.add(node_to_member(n))
        return c

    def remove(self, id: int) -> None:
        p = self.get().find_id(id).store_key()
        self.store.delete(p, True, True)


def node_to_member(n) -> Member:
    """Build a member from its store subtree (child nodes sorted by
    key: /attributes then /raftAttributes) —
    reference cluster_store.go:76-96."""
    m = Member(id=parse_member_id(n.key))
    nodes = n.nodes or []
    if len(nodes) != 2:
        raise ValueError(f"len(nodes) = {len(nodes)}, want 2")
    if nodes[0].key != n.key + ATTRIBUTES_SUFFIX:
        raise ValueError(f"key = {nodes[0].key}, want "
                         f"{n.key + ATTRIBUTES_SUFFIX}")
    m.attributes = Attributes.from_dict(json.loads(nodes[0].value))
    if nodes[1].key != n.key + RAFT_ATTRIBUTES_SUFFIX:
        raise ValueError(f"key = {nodes[1].key}, want "
                         f"{n.key + RAFT_ATTRIBUTES_SUFFIX}")
    m.raft_attributes = RaftAttributes.from_dict(json.loads(nodes[1].value))
    return m
