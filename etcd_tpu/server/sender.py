"""Peer transport: fire-and-forget message sender
(reference etcdserver/cluster_store.go:106-156).

The reference's entire distributed communication backend: POST the
marshaled raftpb.Message to http://<peer>/raft, one goroutine per
message, three attempts with a fresh address pick each try, drops
allowed by contract (server.go:202-206) — safety rests on raft, not
delivery.  Here: one daemon thread per message batch.  ``post_fn`` is
injectable so in-process cluster tests can short-circuit the network
(the reference does the same by swapping sendFunc,
server_test.go:378-384).
"""

from __future__ import annotations

import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Callable

from ..obs import metrics as _obs
from ..wire import MSG_APP, Message
from .cluster import RAFT_PREFIX, ClusterStore

log = logging.getLogger(__name__)

# obs seams (PR 2): every POST attempt is a frame; RTT on success,
# a failure only after the retry budget is spent.  PR 5 adds the
# classic_reconnect row: a cached keep-alive socket found stale and
# re-dialed (the cost connection reuse trades three-way handshakes
# for — visible, not silent).
_M_FRAMES = _obs.registry.counter("etcd_peer_send_frames_total",
                                  path="classic")
_M_RTT = _obs.registry.histogram("etcd_peer_send_seconds",
                                 path="classic")
_M_FAILS = _obs.registry.counter("etcd_peer_send_failures_total",
                                 path="classic")
_M_RECONNECTS = _obs.registry.counter(
    "etcd_peer_send_failures_total", path="classic_reconnect")


def default_post(url: str, data: bytes, timeout: float = 1.0,
                 ssl_context=None) -> bool:
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/protobuf"})
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ssl_context) as resp:
            return resp.status == 204
    except (urllib.error.URLError, OSError):
        return False


def pooled_post(pool, url: str, data: bytes) -> bool:
    """POST over the shared keep-alive cache (peerlink.KeepAlivePool)
    instead of a fresh connection per message — the reference opens a
    transport per attempt (cluster_store.go:118-144), which at
    intra-DC latencies costs more than the frame itself (the dist
    tier measured this in PR 2; PR 5 routes the classic tier through
    the same pool)."""
    from urllib.parse import urlsplit, urlunsplit

    u = urlsplit(url)
    base = urlunsplit((u.scheme, u.netloc, "", "", ""))
    path = u.path or "/"
    out = pool.post(base, base, path, data)
    return out is not None and out[0] == 204


def new_sender(cluster_store: ClusterStore,
               post_fn: Callable[[str, bytes], bool] | None = None,
               leader_stats=None, tls_info=None):
    """Returns send(msgs) that MUST NOT block (server.go:202-206).

    ``leader_stats`` (server/stats.py LeaderStats) records per-follower
    append round-trip latency and failures when provided.
    ``tls_info`` (utils.transport.TLSInfo): when set and non-empty,
    peer POSTs use its client context — cert/key for client-cert auth
    and CA verification against https peers (the reference hands its
    Sender a TLS-capable transport, pkg/transport/listener.go:32-50).
    """
    post = post_fn
    pool_close = lambda: None  # noqa: E731
    if post is None:
        from .peerlink import KeepAlivePool

        ctx = None
        if tls_info is not None and not tls_info.empty():
            ctx = tls_info.client_context()
        pool = KeepAlivePool(
            timeout=1.0, ssl_context=ctx, keep_statuses=(204,),
            on_reconnect=_M_RECONNECTS.inc)
        pool_close = pool.close

        def post(url, data, _pool=pool):
            return pooled_post(_pool, url, data)

    def send(msgs: list[Message]) -> None:
        for m in msgs:
            t = threading.Thread(target=_send_one,
                                 args=(cluster_store, m, post,
                                       leader_stats),
                                 daemon=True)
            t.start()

    # teardown hook: without it the pool caches one keep-alive
    # socket per peer base URL for the process lifetime (no-op when
    # the caller injected its own post_fn)
    send.close = pool_close
    return send


def _send_one(cls: ClusterStore, m: Message, post, stats=None) -> None:
    """Three attempts, address re-picked per try
    (cluster_store.go:118-144)."""
    data = m.marshal()
    track = stats is not None and m.type == MSG_APP
    for _ in range(3):
        u = cls.get().pick(m.to)
        if not u:
            log.warning("etcdhttp: no addr for %x", m.to)
            if track:  # unreachable == failed, for /v2/stats/leader
                stats.fail(m.to)
            _M_FAILS.inc()
            return
        t0 = time.perf_counter()
        _M_FRAMES.inc()
        if post(u + RAFT_PREFIX, data):
            dt = time.perf_counter() - t0
            _M_RTT.observe(dt)
            if track:
                stats.observe(m.to, dt)
            return
    _M_FAILS.inc()
    if track:
        stats.fail(m.to)
