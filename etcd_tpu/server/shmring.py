"""Single-producer single-consumer byte ring over POSIX shared
memory — the committed-stream handoff between a serving shard and
the apply/watch worker (server/roles.py).

Design constraints, in order:

  * **Bounded by construction.**  The ring is a fixed byte span; a
    producer that outruns the consumer drops whole records and
    counts them (``dropped``), it never blocks the raft apply path
    and never grows.  Consumers detect the loss as a gap in the
    COMMIT frame ``seq`` (wire/rolemsg.py) rather than silently
    missing events.
  * **Restart without replay.**  Both cursors live in the shared
    header, so a crashed consumer re-attaches and resumes at its own
    persisted ``tail`` — records applied before the crash are behind
    the cursor and can never be consumed twice (the no-double-apply
    property tests/test_roles.py exercises).
  * **Zero-copy handoff.**  Records are length-prefixed and never
    split across the wrap, so a reader can hand a contiguous
    ``memoryview`` straight to ``frombuffer`` parsers.  ``pop``
    copies by default because the payload outlives the cursor
    advance; ``peek``/``advance`` expose the no-copy path.

Layout: 64-byte header | capacity bytes of records.

  header: magic "SRG1" u32 | generation u32 | head u64 | tail u64 |
          dropped u64 | capacity u64 | reserved

  record: length u32 | payload (contiguous).  A record that would
  straddle the end of the span is preceded by a wrap marker
  (0xFFFFFFFF, written only when >= 4 bytes remain before the
  boundary) and starts at offset 0.

Cursors are monotonic byte offsets (masked modulo capacity on use),
stored as single aligned 8-byte little-endian writes — atomic for
in-order stores on the platforms we run (CPython under the GIL emits
one memcpy per struct.pack_into).  The producer publishes ``head``
only after the payload bytes are fully written; the consumer
publishes ``tail`` only after it has finished (or copied) the
payload.
"""

from __future__ import annotations

import struct
from multiprocessing import shared_memory

from ..wire.schema import BOUNDS, SRG1, FrameError, check_bound

# layout constants come from the declarative schema (wire/schema.py)
_MAGIC = SRG1.magic
_WRAP = 0xFFFFFFFF
_HDR_SIZE = SRG1.header_size
_OFF_MAGIC = SRG1.offsets["magic"]
_OFF_GEN = SRG1.offsets["generation"]
_OFF_HEAD = SRG1.offsets["head"]
_OFF_TAIL = SRG1.offsets["tail"]
_OFF_DROPPED = SRG1.offsets["dropped"]
_OFF_CAP = SRG1.offsets["capacity"]

#: Smallest record span: u32 length prefix. Also the wrap marker size.
_LEN = 4

#: plausibility cap on one length-prefixed record ("srg1.record_len"):
#: the producer drops larger payloads, the consumer treats a larger
#: prefix as corruption and resyncs at the producer cursor
_REC_CAP = BOUNDS["srg1.record_len"]


class ShmRing:
    """One endpoint of the ring. The creator (role supervisor) owns
    the segment lifetime; producers/consumers attach by name."""

    def __init__(self, name: str, capacity: int = 1 << 20, *,
                 create: bool = False):
        if capacity <= 2 * _LEN:
            raise ValueError("capacity too small")
        self.name = name
        if create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=_HDR_SIZE + capacity)
            buf = self._shm.buf
            buf[:_HDR_SIZE] = b"\x00" * _HDR_SIZE
            struct.pack_into("<I", buf, _OFF_MAGIC, _MAGIC)
            struct.pack_into("<Q", buf, _OFF_CAP, capacity)
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        self._attach(self._shm.buf)
        self._owner = create

    def _attach(self, buf) -> None:
        """Validate the ring header and adopt ``buf``.  The segment
        is wire data — a stale, truncated, or foreign segment must
        fail typed, and a corrupt capacity must never size the
        cursor math (cap=0 divides, cap > segment silently
        short-slices)."""
        name = self.name
        if len(buf) < _HDR_SIZE:
            raise FrameError(
                f"shm segment {name!r} too small for ring header")
        (magic,) = struct.unpack_from("<I", buf, _OFF_MAGIC)
        if magic != _MAGIC:
            raise FrameError(f"shm segment {name!r} is not a ring")
        (self.capacity,) = struct.unpack_from("<Q", buf, _OFF_CAP)
        check_bound("srg1.capacity", self.capacity)
        if (self.capacity <= 2 * _LEN
                or self.capacity != len(buf) - _HDR_SIZE):
            raise FrameError(
                f"shm segment {name!r}: implausible ring capacity "
                f"{self.capacity} for {len(buf)}-byte segment")
        self._buf = buf

    @classmethod
    def from_buffer(cls, buf, name: str = "<buffer>") -> "ShmRing":
        """Attach to a raw ring image (tests / the schema-driven
        fuzzer): same typed header validation as a shm attach, no
        shared-memory segment behind it."""
        self = cls.__new__(cls)
        self.name = name
        self._shm = None
        self._owner = False
        self._attach(memoryview(buf) if isinstance(buf, (bytes,
                     bytearray)) else buf)
        return self

    # -- header accessors ---------------------------------------------------

    def _get(self, off: int) -> int:
        (v,) = struct.unpack_from("<Q", self._buf, off)
        return v

    def _put(self, off: int, v: int) -> None:
        struct.pack_into("<Q", self._buf, off, v)

    @property
    def head(self) -> int:
        return self._get(_OFF_HEAD)

    @property
    def tail(self) -> int:
        return self._get(_OFF_TAIL)

    @property
    def dropped(self) -> int:
        return self._get(_OFF_DROPPED)

    def bump_generation(self) -> int:  # owner: shmring-producer
        """Producer calls on (re-)attach so observers can tell a
        restarted shard from a stalled one."""
        (g,) = struct.unpack_from("<I", self._buf, _OFF_GEN)
        struct.pack_into("<I", self._buf, _OFF_GEN, (g + 1) & 0xFFFFFFFF)
        return g + 1

    @property
    def generation(self) -> int:
        (g,) = struct.unpack_from("<I", self._buf, _OFF_GEN)
        return g

    def __len__(self) -> int:
        return self.head - self.tail

    # -- producer -----------------------------------------------------------

    def push(self, payload) -> bool:  # owner: shmring-producer
        """Appends one record; returns False (and counts a drop) if
        it doesn't fit. Records larger than capacity - 2*_LEN - 1
        can never fit and always drop."""
        n = len(payload)
        if n > _REC_CAP:
            # over the schema's srg1.record_len cap: the consumer
            # would treat the prefix as corruption, so drop loudly
            # here instead of poisoning the ring
            self._put(_OFF_DROPPED, self.dropped + 1)
            return False
        head, tail = self.head, self.tail
        cap = self.capacity
        pos = head % cap
        to_end = cap - pos
        need = _LEN + n
        if to_end < need:
            # wrap: burn the rest of the span (+ marker if room)
            need = to_end + _LEN + n
            marker = to_end >= _LEN
        else:
            marker = False
        # full-ring guard: leave one byte free so head==tail is
        # unambiguously "empty"
        if need >= cap - (head - tail):
            self._put(_OFF_DROPPED, self.dropped + 1)
            return False
        buf = self._buf
        if to_end < _LEN + n:
            if marker:
                struct.pack_into("<I", buf, _HDR_SIZE + pos, _WRAP)
            pos = 0
        struct.pack_into("<I", buf, _HDR_SIZE + pos, n)
        buf[_HDR_SIZE + pos + _LEN:_HDR_SIZE + pos + _LEN + n] = payload
        # publish only after the payload bytes are in place
        self._put(_OFF_HEAD, head + need)
        return True

    # -- consumer -----------------------------------------------------------

    def _peek(self) -> tuple[memoryview, int] | None:  # owner: shmring-consumer
        """Returns (payload view, consumed byte span) or None."""
        head, tail = self.head, self.tail
        if head == tail:
            return None
        cap = self.capacity
        pos = tail % cap
        to_end = cap - pos
        skipped = 0
        if to_end < _LEN:
            # producer wrapped without room for a marker
            skipped = to_end
            pos = 0
        else:
            (n,) = struct.unpack_from("<I", self._buf,
                                      _HDR_SIZE + pos)
            if n == _WRAP:
                skipped = to_end
                pos = 0
            elif _LEN + n > to_end:
                # length prefix would run past the span boundary:
                # corrupt header, resync at the producer cursor
                self._put(_OFF_TAIL, head)
                return None
        (n,) = struct.unpack_from("<I", self._buf, _HDR_SIZE + pos)
        if _LEN + n > cap - pos or n == _WRAP or n > _REC_CAP:
            self._put(_OFF_TAIL, head)
            return None
        view = self._buf[_HDR_SIZE + pos + _LEN:
                         _HDR_SIZE + pos + _LEN + n]
        return view, skipped + _LEN + n

    def pop(self) -> bytes | None:  # owner: shmring-consumer
        """Copies out the next record and advances, or None if
        empty."""
        got = self._peek()
        if got is None:
            return None
        view, span = got
        payload = bytes(view)
        view.release()
        self._put(_OFF_TAIL, self.tail + span)
        return payload

    def close(self) -> None:
        self._buf = None
        if self._shm is not None:
            self._shm.close()

    def unlink(self) -> None:
        if self._shm is None:
            return
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
