"""Array-form GroupEntry replay for multi-group restart.

Round-2 weakness #5: the co-hosted server replayed its WAL through
the device lane and then walked every entry with
``GroupEntry.unmarshal`` and a winners dict — reintroducing the
per-record scalar loop the project exists to kill (at 1M entries,
the restart bottleneck).  This module keeps the whole pass in arrays:

1. envelope fields come from ONE native sweep over the entry-data
   spans (native/walscan.cc:etcd_ge_scan; Python fallback when the
   toolchain is absent),
2. last-record-wins dedup per (group, gindex) — the replay-overwrite
   semantics of wal.go:171-175 generalized to the group axis — is a
   sort + run-boundary scan,
3. frontier / ballot selection is a reverse argmax.

Payload bytes stay in the blob; only the (rare) committed winners
that actually apply to the store materialize Python objects.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import native
from ..wire import GroupEntry


@dataclass(slots=True)
class GEStream:
    """Struct-of-arrays view of a replayed GroupEntry record stream."""

    seq: np.ndarray       # int64 [N] WAL entry index per record
    kind: np.ndarray      # int64 [N]
    group: np.ndarray     # int64 [N]
    gindex: np.ndarray    # int64 [N]
    gterm: np.ndarray     # int64 [N]
    # payloads: either spans into ``blob`` or a list of bytes
    poff: np.ndarray | None
    plen: np.ndarray | None
    blob: np.ndarray | None
    plist: list | None

    def __len__(self) -> int:
        return self.kind.size

    def payload(self, i: int) -> bytes | None:
        if self.plist is not None:
            return self.plist[i]
        ln = int(self.plen[i])
        if ln == 0:
            return None
        o = int(self.poff[i])
        return self.blob[o:o + ln].tobytes()

    # -- batch selections --------------------------------------------------

    def last_of_kind(self, kind: int) -> int:
        """Position of the last record of ``kind`` (-1 if none)."""
        hits = np.nonzero(self.kind == kind)[0]
        return int(hits[-1]) if hits.size else -1

    def winner_positions(self) -> np.ndarray:
        """Positions (ascending = stream order) of the kind-0 records
        that win last-record-wins dedup for their (group, gindex)."""
        pos = np.nonzero(self.kind == 0)[0]
        if pos.size == 0:
            return pos
        key = self.group[pos].astype(np.int64) * (1 << 40) \
            + self.gindex[pos].astype(np.int64)
        order = np.argsort(key, kind="stable")
        k_sorted = key[order]
        last_in_run = np.ones(k_sorted.size, bool)
        last_in_run[:-1] = k_sorted[1:] != k_sorted[:-1]
        return np.sort(pos[order[last_in_run]])


def scan(block_or_entries, blob: np.ndarray | None = None) -> GEStream:
    """Build a :class:`GEStream` from either a device-replay
    ``EntryBlock`` (native array sweep — no per-entry objects) or a
    host-replay ``list[Entry]`` (Python fallback loop)."""
    from ..wal.replay_device import EntryBlock

    if isinstance(block_or_entries, EntryBlock):
        b = block_or_entries
        if native.available():
            kind, group, gindex, gterm, poff, plen = native.ge_scan(
                b.blob, b.data_off, b.data_len)
            return GEStream(seq=b.index.astype(np.int64), kind=kind,
                            group=group, gindex=gindex, gterm=gterm,
                            poff=poff, plen=plen, blob=b.blob,
                            plist=None)
        entries = b.entries()
    else:
        entries = block_or_entries

    n = len(entries)
    seq = np.empty(n, np.int64)
    kind = np.empty(n, np.int64)
    group = np.empty(n, np.int64)
    gindex = np.empty(n, np.int64)
    gterm = np.empty(n, np.int64)
    plist: list[bytes | None] = []
    for i, e in enumerate(entries):
        ge = GroupEntry.unmarshal(e.data)
        seq[i] = e.index
        kind[i] = ge.kind
        group[i] = ge.group
        gindex[i] = ge.gindex
        gterm[i] = ge.gterm
        plist.append(ge.payload)
    return GEStream(seq=seq, kind=kind, group=group, gindex=gindex,
                    gterm=gterm, poff=None, plen=None, blob=None,
                    plist=plist)


def seed_log_arrays(stream: GEStream, winners: np.ndarray,
                    frontier: np.ndarray, fterms: np.ndarray,
                    g: int, cap: int):
    """Rebuild the engine's per-group log window from the replayed
    tail, entirely in arrays.

    Returns ``(log_term [g, cap], last [g], tail_positions)`` where
    slot 0 of each row carries the frontier term, slots 1.. carry the
    CONTIGUOUS run of winner terms above the frontier (a gap ends the
    run — a non-contiguous higher entry is unreachable garbage from
    a dropped batch), and ``tail_positions`` are the stream positions
    of the retained tail entries (callers hydrate their payload
    rings from these).
    """
    log_term = np.zeros((g, cap), np.int32)
    log_term[:, 0] = fterms
    last = frontier.astype(np.int64).copy()
    if winners.size == 0:
        return log_term, last, winners
    wg = stream.group[winners]
    wi = stream.gindex[winners]
    wt = stream.gterm[winners]
    rel = wi - frontier[wg]
    tail = (rel >= 1) & (rel < cap)
    if not tail.any():
        return log_term, last, winners[:0]
    tg, tt, tr = wg[tail], wt[tail], rel[tail].astype(np.int64)
    # presence matrix + cumulative product = per-group contiguous run
    # length from slot 1 (restart-only [g, cap] scratch; 100k groups
    # x cap 1024 is ~100 MB transiently)
    pres = np.zeros((g, cap), np.uint8)
    pres[tg, tr] = 1
    runlen = np.cumprod(pres[:, 1:], axis=1).sum(
        axis=1).astype(np.int64)
    last += runlen
    keep = tr <= runlen[tg]
    log_term[tg[keep], tr[keep]] = tt[keep]
    return log_term, last, np.sort(winners[tail][keep])
