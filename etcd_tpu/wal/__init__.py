"""L3* write-ahead log: durable record log + batched device replay.

``WAL`` is the host read/write path (reference wal/wal.go seam:
Create/OpenAtIndex/ReadAll/Save/SaveEntry/SaveState/Cut/Sync/Close).
``replay`` adds the TPU-native bulk path: parallel record framing +
device CRC verification + GF(2) chain fix-up instead of the
reference's strictly-sequential decode loop.
"""

from .errors import (
    CRCMismatchError,
    FileNotFoundError_,
    IndexNotFoundError,
    MetadataConflictError,
    TornTailError,
    WALError,
)
from .wal import (
    CRC_TYPE,
    ENTRY_TYPE,
    METADATA_TYPE,
    STATE_TYPE,
    WAL,
    exist,
    parse_wal_name,
    search_index,
    select_segments,
    is_valid_seq,
    wal_name,
)

__all__ = [
    "WAL",
    "exist",
    "wal_name",
    "parse_wal_name",
    "search_index",
    "select_segments",
    "is_valid_seq",
    "METADATA_TYPE",
    "ENTRY_TYPE",
    "STATE_TYPE",
    "CRC_TYPE",
    "WALError",
    "MetadataConflictError",
    "FileNotFoundError_",
    "IndexNotFoundError",
    "CRCMismatchError",
    "TornTailError",
]
