"""Measured per-stage backend router for the replay data plane.

The r05 round lost on two fronts the reference never loses on: with
no accelerator the framework's e2e replay still shipped every record
through the JAX-CPU bit-matmul (0.021x the 2014 one-core Go binary),
and on a TPU session the restart replay went 24x SLOWER through the
tunnel-bound device than the identical stage on the host path —
both because the replay path picked its backend statically.  This
module generalizes the one measured auto-choice the repo already had
(ops/crc_kernel's snapshot-hash race, "config3 auto") into a reusable
router for every replay-shaped stage (restart replay, bulk replay,
the bench e2e row):

- **probe**: a cheap startup measurement of the three pipeline legs —
  host fused scan (native scan_verify over a small synthetic stream),
  H2D shipping, and the device CRC verify — cached in-process and,
  when ``cache_path`` is given, on disk so restarts reuse it.
- **route**: ``host`` (fused single-pass native scan), ``device``
  (monolithic batched device verify), or ``stream`` (the chunked
  double-buffered overlap pipeline, wal/replay_device.py).  The
  device lanes are chosen ONLY when the probed pipeline floor —
  min(host_scan, h2d, device_verify), what the overlap pipeline can
  sustain — beats the probed host throughput, so a present-but-slow
  accelerator can never regress replay below the host path.
- **override**: ``ETCD_REPLAY_BACKEND=host|device|stream`` wins over
  the probe unconditionally (operator escape hatch; read per
  decision, so tests and long-lived processes can flip it).

Every decision lands in the obs registry (``etcd_replay_backend_route``
per stage, ``etcd_replay_probe_bytes_per_sec`` per leg) and in
``snapshot()`` — the form bench.py embeds in its artifact rows so a
reviewer can attribute a regression to routing vs kernel.

Import-light by design: jax only loads inside the device probe, so
the CPU-pinned server path can route without initializing a backend.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

import numpy as np

from .. import native
from ..obs import metrics as _obs

log = logging.getLogger(__name__)

#: the operator override (host | device | stream; aliases accepted)
ENV_KNOB = "ETCD_REPLAY_BACKEND"

ROUTES = ("host", "device", "stream")

_ALIASES = {
    "native-host": "host", "native_host": "host", "cpu": "host",
    "streaming-device": "stream", "streaming": "stream",
    "tpu": "device",
}

#: default streaming chunk size — the scripts/replay_bench.py sweep
#: showed host-path throughput flat from 4 MiB up (1 MiB pays ~2-3%
#: more per-chunk overhead), and 4 MiB keeps at most ~12 MiB of scan
#: arrays in flight at double-buffer depth 2
DEFAULT_CHUNK_BYTES = 4 << 20

#: below this stream size the device lanes can't amortize their jit
#: compile (seconds), so the router answers "host" WITHOUT probing —
#: a tiny-WAL restart must not initialize a jax backend just to be
#: told what the size already says (server.py's historical threshold)
DEVICE_MIN_BYTES = 8 << 20

#: on-disk probe cache lifetime — a stale measurement pinning the
#: route would recreate the static-choice failure mode this module
#: exists to kill
DEFAULT_CACHE_TTL_S = 24 * 3600

# probe shapes: small enough to be a startup blip (~1 MiB host blob,
# one [2048, 384] device batch), large enough to amortize call setup
_PROBE_ENTRIES = 4096
_PROBE_PAYLOAD = 256
_PROBE_ROWS = 2048
_PROBE_WIDTH = 384

_PROBE_LEGS = ("host_scan", "host_frame", "h2d", "device_verify")


def _probe_host_default() -> dict | None:
    """Host-leg throughputs (bytes/s) over a synthetic stream:
    ``host_scan_bps`` is the FUSED pass (frame + parse + CRC — what
    the host route runs), ``host_frame_bps`` the frame/parse-only
    sweep (the streaming pipeline's host stage; the CRC rides the
    device there).  None when the native toolchain is absent."""
    if not native.available():
        return None
    blob = native.wal_gen(_PROBE_ENTRIES, _PROBE_PAYLOAD,
                          start_index=1, seed=0)

    def best_of2(fn):
        best = float("inf")
        for _ in range(2):  # best-of-2: first pass pays page faults
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return blob.nbytes / max(best, 1e-9)

    return {"host_scan_bps":
            best_of2(lambda: native.scan_verify(blob, seed=0)),
            "host_frame_bps":
            best_of2(lambda: native.wal_scan(blob))}


def _probe_device_default() -> dict | None:
    """H2D and device-verify throughput (bytes/s), or None when the
    default backend is the host CPU (no accelerator to route to).
    Raises on a broken device — the caller maps that to the host
    route."""
    import jax

    if jax.default_backend() == "cpu":
        return None
    from ..ops.crc_device import raw_crc_batch

    rows = np.zeros((_PROBE_ROWS, _PROBE_WIDTH), np.uint8)
    jax.block_until_ready(jax.device_put(rows))  # warm the transfer
    t0 = time.perf_counter()
    shipped = jax.block_until_ready(jax.device_put(rows))
    h2d = rows.nbytes / max(time.perf_counter() - t0, 1e-9)
    jax.block_until_ready(raw_crc_batch(shipped))  # compile warmup
    t0 = time.perf_counter()
    jax.block_until_ready(raw_crc_batch(shipped))
    verify = rows.nbytes / max(time.perf_counter() - t0, 1e-9)
    return {"h2d_bps": h2d, "device_verify_bps": verify}


class BackendPolicy:
    """One process's replay-routing state: probe results + decisions.

    ``probe_host`` / ``probe_device`` are injectable for tests (a
    simulated slow or broken device must provably select the host
    route without hardware in the loop).
    """

    def __init__(self, cache_path: str | None = None,
                 probe_host=None, probe_device=None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 device_min_bytes: int = DEVICE_MIN_BYTES,
                 cache_ttl_s: float = DEFAULT_CACHE_TTL_S):
        self._lock = threading.Lock()
        self.cache_path = cache_path
        self.chunk_bytes = int(chunk_bytes)
        self.device_min_bytes = int(device_min_bytes)
        self.cache_ttl_s = float(cache_ttl_s)
        self._probe_host_fn = probe_host or _probe_host_default
        self._probe_device_fn = probe_device or _probe_device_default
        self._probe: dict | None = None
        self.decisions: dict[str, dict] = {}
        _obs.registry.gauge("etcd_replay_stream_chunk_bytes").set(
            self.chunk_bytes)

    # -- probe ------------------------------------------------------------

    def probe(self) -> dict:
        """Measure (or recall) the per-leg throughputs.  One probe per
        process; ``cache_path`` extends the reuse across restarts."""
        with self._lock:
            if self._probe is not None:
                return self._probe
            p = self._load_cache()
            if p is None:
                p = self._measure()
                self._save_cache(p)
            else:
                p["source"] = "cache"
            self._probe = p
        for leg in _PROBE_LEGS:
            _obs.registry.gauge(
                "etcd_replay_probe_bytes_per_sec", leg=leg).set(
                p.get(f"{leg}_bps") or 0.0)
        return p

    def _measure(self) -> dict:
        p: dict = {"source": "probe",
                   "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
                   "ts_epoch": time.time()}
        try:
            ph = self._probe_host_fn()
        except Exception as e:  # no native tier: the device lanes
            log.warning("backend_policy: host probe failed: %r", e)
            ph = None  # may still carry the replay
            p["host_error"] = repr(e)[:200]
        if isinstance(ph, dict):
            p["host_scan_bps"] = ph.get("host_scan_bps")
            p["host_frame_bps"] = ph.get("host_frame_bps",
                                         ph.get("host_scan_bps"))
        else:  # injected scalar probes: one number for both legs
            p["host_scan_bps"] = ph
            p["host_frame_bps"] = ph
        try:
            dev = self._probe_device_fn()
        except Exception as e:
            # a broken/unreachable accelerator must degrade to the
            # host path, never crash a restart
            log.warning("backend_policy: device probe failed: %r", e)
            dev = None
            p["device_error"] = repr(e)[:200]
        p["h2d_bps"] = (dev or {}).get("h2d_bps")
        p["device_verify_bps"] = (dev or {}).get("device_verify_bps")
        return p

    def _load_cache(self) -> dict | None:
        if not self.cache_path:
            return None
        try:
            with open(self.cache_path) as fh:
                doc = json.load(fh)
            if doc.get("version") != 1:
                return None
            p = dict(doc["probe"])
            age = time.time() - float(p.get("ts_epoch", 0))
            if not 0 <= age <= self.cache_ttl_s:
                log.info("backend_policy: probe cache is %.0fh old; "
                         "re-probing", age / 3600)
                return None
            return p
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _save_cache(self, p: dict) -> None:
        if not self.cache_path:
            return
        if "device_error" in p or "host_error" in p:
            # a probe taken during an outage must not pin the route
            # for every later restart — errors stay process-local
            return
        try:
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"version": 1, "probe": p}, fh)
            os.replace(tmp, self.cache_path)
        except OSError as e:  # cache is an optimization, never fatal
            log.warning("backend_policy: cache write failed: %r", e)

    # -- routing ----------------------------------------------------------

    def route(self, stage: str, size_bytes: int | None = None,
              strict_device: bool = False) -> str:
        """Pick host | device | stream for one replay-shaped stage.

        Precedence: env override > (strict_device: the operator's
        --storage-backend=tpu promise) > size gate > probe comparison
        > host.  The decision is recorded per ``stage`` in the
        registry and in :attr:`decisions`.
        """
        route, why = self._env_route()
        if route is None and not strict_device \
                and size_bytes is not None \
                and size_bytes < self.device_min_bytes:
            # tiny streams: the device lanes can't amortize their jit
            # compile, and the device probe would initialize a jax
            # backend on the restart path — answer without either
            route, why = "host", (
                f"size {int(size_bytes)} B < device threshold "
                f"{self.device_min_bytes} B")
        if route is None:
            probe = self.probe()
            if strict_device:
                route, why = "stream", "strict_device"
            elif probe.get("device_verify_bps") is None:
                route, why = "host", (
                    "no usable accelerator"
                    if "device_error" not in probe
                    else f"device probe failed: {probe['device_error']}")
            else:
                # host route sustains the FUSED pass; the pipeline
                # sustains min over its legs — frame-only host scan
                # (CRC rides the device), H2D, device verify
                host = probe.get("host_scan_bps") or 0.0
                floor = min(x for x in (
                    probe.get("host_frame_bps") or float("inf"),
                    probe["h2d_bps"],
                    probe["device_verify_bps"]))
                if floor > host:
                    route, why = "stream", (
                        f"pipeline floor {floor:.3g} B/s > host "
                        f"{host:.3g} B/s")
                else:
                    route, why = "host", (
                        f"pipeline floor {floor:.3g} B/s <= host "
                        f"{host:.3g} B/s")
        return self.note(stage, route, why, size_bytes=size_bytes)

    def note(self, stage: str, route: str, why: str,
             size_bytes: int | None = None) -> str:
        """Record — or CORRECT — a stage's decision (registry gauges
        + :attr:`decisions`).  Callers that end up on a different
        lane than the one routed (a failed fast lane falling back to
        the repair path, a bench remap) must call this so the
        recorded route is always the lane that actually ran — the
        whole point of the decision artifact is attribution."""
        decision = {"route": route, "why": why, "stage": stage}
        if size_bytes is not None:
            decision["size_bytes"] = int(size_bytes)
        elif stage in self.decisions \
                and "size_bytes" in self.decisions[stage]:
            decision["size_bytes"] = \
                self.decisions[stage]["size_bytes"]
        self.decisions[stage] = decision
        for r in ROUTES:
            _obs.registry.gauge("etcd_replay_backend_route",
                                stage=stage, route=r).set(
                1.0 if r == route else 0.0)
        return route

    def _env_route(self) -> tuple[str | None, str | None]:
        raw = os.environ.get(ENV_KNOB, "").strip().lower()
        if not raw:
            return None, None
        route = _ALIASES.get(raw, raw)
        if route not in ROUTES:
            log.warning("backend_policy: ignoring %s=%r (want one of "
                        "%s)", ENV_KNOB, raw, "/".join(ROUTES))
            return None, None
        return route, f"env {ENV_KNOB}={raw}"

    def snapshot(self) -> dict:
        """Probe numbers + per-stage decisions, JSON-ready — the
        ``policy_probe`` sub-object bench.py embeds in its rows."""
        out = {"chunk_bytes": self.chunk_bytes,
               "decisions": dict(self.decisions)}
        if self._probe is not None:
            out["probe"] = dict(self._probe)
        return out


# -- process-wide singleton ---------------------------------------------------

_policy: BackendPolicy | None = None
_policy_lock = threading.Lock()


def get_policy() -> BackendPolicy:
    """The process's router (probe runs once, on first routed call).
    ``ETCD_REPLAY_PROBE_CACHE`` names an optional on-disk cache file
    so short-lived processes (restart loops) skip re-probing."""
    global _policy
    with _policy_lock:
        if _policy is None:
            _policy = BackendPolicy(
                cache_path=os.environ.get("ETCD_REPLAY_PROBE_CACHE")
                or None)
        return _policy


def set_policy(p: BackendPolicy | None) -> None:
    """Swap (or, with None, reset) the process router — tests."""
    global _policy
    with _policy_lock:
        _policy = p


__all__ = [
    "BackendPolicy", "DEFAULT_CHUNK_BYTES", "ENV_KNOB", "ROUTES",
    "get_policy", "set_policy",
]
