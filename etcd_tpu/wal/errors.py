"""WAL error vocabulary (reference wal/wal.go:44-49).

The wire layer owns the base CRC error (like walpb.ErrCRCMismatch,
wal/walpb/record.go:20); the WAL's ``CRCMismatchError`` subclasses both
it and ``WALError`` so callers can treat all replay corruption
uniformly with ``except WALError``.
"""

from ..wire.proto import CRCMismatchError as WireCRCMismatchError

__all__ = [
    "WALError",
    "MetadataConflictError",
    "FileNotFoundError_",
    "IndexNotFoundError",
    "CRCMismatchError",
    "TornTailError",
]


class WALError(Exception):
    pass


class TornTailError(WALError):
    """The stream ends mid-record (the reference's io.ErrUnexpectedEOF
    lane, wal/decoder.go:30-35): every byte from the failing record's
    start to the end of the file chain belongs to the torn record.

    All three scanners (host decoder, python scan, native scan) raise
    this exact type so strict-mode replay policy matches on the type,
    never on message text.
    """


class CRCMismatchError(WALError, WireCRCMismatchError):
    """Rolling checksum mismatch during replay (ErrCRCMismatch)."""


class MetadataConflictError(WALError):
    """Conflicting metadata found (ErrMetadataConflict)."""


class FileNotFoundError_(WALError):
    """No WAL file found for the requested index (ErrFileNotFound)."""


class IndexNotFoundError(WALError):
    """Requested index not present in the WAL (ErrIndexNotFound)."""
