"""WAL error vocabulary (reference wal/wal.go:44-49).

``CRCMismatchError`` is re-exported from the wire layer, where the
reference also defines it (wal/walpb/record.go:20), so the L2 codec
never imports upward.
"""

from ..wire.proto import CRCMismatchError

__all__ = [
    "WALError",
    "MetadataConflictError",
    "FileNotFoundError_",
    "IndexNotFoundError",
    "CRCMismatchError",
]


class WALError(Exception):
    pass


class MetadataConflictError(WALError):
    """Conflicting metadata found (ErrMetadataConflict)."""


class FileNotFoundError_(WALError):
    """No WAL file found for the requested index (ErrFileNotFound)."""


class IndexNotFoundError(WALError):
    """Requested index not present in the WAL (ErrIndexNotFound)."""
