"""WAL error vocabulary (reference wal/wal.go:44-49)."""


class WALError(Exception):
    pass


class MetadataConflictError(WALError):
    """Conflicting metadata found (ErrMetadataConflict)."""


class FileNotFoundError_(WALError):
    """No WAL file found for the requested index (ErrFileNotFound)."""


class IndexNotFoundError(WALError):
    """Requested index not present in the WAL (ErrIndexNotFound)."""


class CRCMismatchError(WALError):
    """Rolling checksum mismatch (ErrCRCMismatch)."""
