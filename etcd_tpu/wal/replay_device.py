"""Device-batched WAL replay (north-star config 1).

The reference replays a WAL strictly sequentially: per record, read a
length prefix, proto-unmarshal, update a rolling CRC, compare
(wal/wal.go:164-216, wal/decoder.go:28-47).  Here the same replay is a
three-stage pipeline:

1. **Host framing** (native/walscan.cc, or a numpy fallback): one
   sweep produces per-record arrays — type, stored CRC, data span,
   entry index/term/type.  Byte-granular and branchy: stays native.
2. **Device verification**: payload rows are right-aligned into an
   ``[N, L]`` buffer; every record's raw CRC is one MXU bit-matmul
   (ops/crc_device.py) and every chain link is checked in parallel
   (the chain is sequential only through its *stored* values, which
   the file already holds — so verification parallelizes even though
   computation of the chain did not).
3. **Host semantics**: metadata consistency, HardState selection,
   entry dedup-by-index (wal/wal.go:171-175) — cheap array ops on the
   scan output, no per-record Python objects.

The replay result keeps entries as an :class:`EntryBlock` — a
struct-of-arrays view into the raw blob, which is both the cheap form
(no 1M-object materialization) and the device-resident form the
batched raft engine consumes.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from queue import Empty, Full, Queue

import numpy as np

from .. import native
from ..obs import metrics as _obs
from ..obs.devledger import ledger as _ledger
from ..wire import Entry, HardState
from ..wire.proto import ProtoError
from .backend_policy import DEFAULT_CHUNK_BYTES, get_policy
from .errors import (
    CRCMismatchError,
    FileNotFoundError_,
    IndexNotFoundError,
    MetadataConflictError,
    TornTailError,
    WALError,
)
from .wal import (
    CRC_TYPE,
    ENTRY_TYPE,
    METADATA_TYPE,
    STATE_TYPE,
    WAL,
    select_segments,
)


@dataclass(slots=True)
class EntryBlock:
    """Struct-of-arrays entry log slice backed by the WAL blob.

    The array form mirrors the device-resident log layout (SURVEY
    §7 "fixed-width array encodings for device residency"): callers
    can ship ``(index, term, type)`` straight to HBM and keep payload
    bytes host-side until apply.
    """

    index: np.ndarray      # uint64 [N]
    term: np.ndarray       # uint64 [N]
    type: np.ndarray       # uint64 [N]
    data_off: np.ndarray   # uint64 [N] into blob
    data_len: np.ndarray   # uint64 [N]
    blob: np.ndarray       # uint8, the raw WAL byte stream
    last_crc: int = 0      # stored CRC of the stream's final record
                           # (seeds WAL.open_at_end for appending)

    def __len__(self) -> int:
        return self.index.size

    def entry(self, i: int) -> Entry:
        """Materialize one Entry object (host convenience)."""
        o, l = int(self.data_off[i]), int(self.data_len[i])
        return Entry.unmarshal(self.blob[o:o + l].tobytes())

    def entries(self) -> list[Entry]:
        return [self.entry(i) for i in range(len(self))]


def _parse_record_span(raw: bytes, base: int, rlen: int):
    """Parse one Record in place, returning exact field positions.

    Walks the proto fields directly (the field loop of
    ``wire.proto.Record.unmarshal``) so the returned data span is the
    byte range the encoder actually wrote — a substring search can
    false-match payload bytes that also occur inside the type/crc
    varint envelope, which is how the native scanner avoids it too
    (walscan.cc tracks offsets while decoding).

    Returns ``(type, crc, data_off_abs, data_len)``.
    """
    from ..wire.proto import _expect_wt, _skip_field, _tag, uvarint

    end = base + rlen
    rtype = crc = 0
    doff, dlen = base, 0
    pos = base
    while pos < end:
        # _tag rejects field number 0 exactly like Record.unmarshal —
        # both replay lanes must agree on record validity
        fnum, wt, pos = _tag(raw, pos)
        if fnum == 1:
            _expect_wt(fnum, wt, 0)  # corrupt framing aborts, never
            rtype, pos = uvarint(raw, pos)  # masks (proto.py parity)
        elif fnum == 2:
            _expect_wt(fnum, wt, 0)
            crc, pos = uvarint(raw, pos)
        elif fnum == 3:
            _expect_wt(fnum, wt, 2)
            dlen, pos = uvarint(raw, pos)
            doff = pos
            pos += dlen
        else:
            pos = _skip_field(raw, pos, wt)
        if pos > end:
            raise WALError("record field overruns frame")
    return rtype, crc, doff, dlen


def _scan_python(blob: np.ndarray):
    """Pure-Python framing fallback mirroring native.wal_scan."""
    raw = blob.tobytes()
    pos, n = 0, len(raw)
    types, crcs, doffs, dlens, eidxs, eterms, etypes = \
        [], [], [], [], [], [], []
    while pos < n:
        if pos + 8 > n:
            raise TornTailError("truncated frame header")
        rlen = int.from_bytes(raw[pos:pos + 8], "little", signed=True)
        pos += 8
        if rlen < 0:
            raise WALError(f"negative record length {rlen}")
        if rlen > n - pos:
            raise TornTailError("truncated record")
        rtype, crc, doff, dlen = _parse_record_span(raw, pos, rlen)
        types.append(rtype)
        crcs.append(crc)
        doffs.append(doff)
        dlens.append(dlen)
        if rtype == ENTRY_TYPE and dlen:
            e = Entry.unmarshal(raw[doff:doff + dlen])
            eidxs.append(e.index)
            eterms.append(e.term)
            etypes.append(e.type)
        else:
            eidxs.append(0)
            eterms.append(0)
            etypes.append(0)
        pos += rlen
    return (np.asarray(types, np.int64), np.asarray(crcs, np.uint32),
            np.asarray(doffs, np.uint64), np.asarray(dlens, np.uint64),
            np.asarray(eidxs, np.uint64), np.asarray(eterms, np.uint64),
            np.asarray(etypes, np.uint64))


def _accelerator_absent() -> bool:
    """True when JAX's default backend is the host CPU — the batched
    device CRC then has no hardware to win on and the native
    sequential verifier is the fast path (VERDICT r4 #2).  Imports
    jax lazily: callers on the CPU-pinned server path already hold an
    initialized jax, and the device path imports it regardless."""
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # pragma: no cover - no jax at all
        return True


def _pad_rows_numpy(blob, doff, dlen, width):
    n = doff.size
    out = np.zeros((n, width), np.uint8)
    for i in range(n):
        o, l = int(doff[i]), int(dlen[i])
        out[i, width - l:] = blob[o:o + l]
    return out


# -- streaming pipeline (PR 3 tentpole) --------------------------------------
#
# The monolithic device lane serializes scan -> full H2D -> verify, so
# e2e throughput is the *harmonic* mean of the stages — on a slow
# transport it collapses to the transport rate (the r05 0.021x row).
# The streaming lane splits the blob into fixed-size chunks and
# overlaps host framing of chunk k+1 with H2D of chunk k and device
# CRC verify of chunk k-1 (GPipe-style double buffering applied to
# the durability tier), so throughput approaches min(stage) instead.
# GF(2) seed injection makes per-chunk verification composable: chunk
# c's chain seeds from chunk c-1's last *stored* CRC, exactly the
# induction the batched verifier already relies on per link.

_CHUNK_HIST = {
    stage: _obs.registry.histogram("etcd_replay_stream_chunk_seconds",
                                   stage=stage)
    for stage in ("scan", "h2d", "verify")}


class DeviceTransport:
    """The H2D + device-verify legs of the streaming pipeline.

    An injectable seam: production ships padded rows with
    ``jax.device_put`` and dispatches the injected-seed CRC matmul;
    the deterministic pipeline tests swap in a fake with programmable
    per-chunk latencies to prove the overlap (and the bit-exactness
    of the stitched chain) without hardware in the loop.
    ``verify`` must only *dispatch* (async); ``collect`` blocks.
    """

    def ship(self, rows: np.ndarray):
        import jax

        return jax.device_put(rows)

    def verify(self, shipped, stored: np.ndarray):
        from ..ops.crc_device import chain_links_injected, raw_crc_batch

        return chain_links_injected(raw_crc_batch(shipped), stored)

    def collect(self, handle) -> np.ndarray:
        return np.asarray(handle)


def _raise_native(e: native.NativeError, record_base: int = 0):
    """Map a native scan/verify failure onto the WAL error vocabulary
    (by return CODE, never message text), naming the first bad record
    in stream-global terms."""
    if e.code == native.CRC_MISMATCH:
        bad = record_base + getattr(e, "bad_index", 0)
        raise CRCMismatchError(
            f"crc chain broken at record {bad} "
            f"(stored={getattr(e, 'bad_stored', 0):#x})") from e
    if e.code == native.TRUNCATED:
        raise TornTailError(str(e)) from e
    raise WALError(str(e)) from e


def _width_classes(dlen_v: np.ndarray) -> np.ndarray:
    """Quantized padded row width per record (4 spare bytes for the
    injected seed): multiples of 128 up to 2 KiB, powers of two
    above — bounds the compiled-shape count while keeping one huge
    record from inflating every row's padding."""
    need = dlen_v.astype(np.int64) + 4
    return np.where(
        need <= 2048,
        np.maximum(128, -(-need // 128) * 128),
        np.int64(1) << np.ceil(
            np.log2(np.maximum(need, 1).astype(np.float64))
        ).astype(np.int64))


def _dispatch_chunk_verify(blob, crcs, doff, dlen, prev, transport,
                           byte_budget: int, ledger_stage: str):
    """Pad + seed-inject one scanned chunk's records and *dispatch*
    the device chain verify (one shipment per width class inside the
    chunk).  Returns ``[(sel, n_real, handle), ...]`` for a later
    blocking collect — the caller keeps scanning/shipping while the
    device works."""
    from ..ops.crc_device import inject_seeds

    stored = np.ascontiguousarray(crcs, np.uint32)
    dlen_v = np.ascontiguousarray(dlen, np.uint64)
    prev = np.ascontiguousarray(prev, np.uint32)
    wcls = _width_classes(dlen_v)
    out = []
    t0 = time.perf_counter()
    for w in np.unique(wcls):
        w = int(w)
        rows_idx = np.nonzero(wcls == w)[0]
        rpc = max(1, min(1 << 17, byte_budget // w))
        rpc = min(rpc, max(8, 1 << (rows_idx.size - 1).bit_length()))
        for lo in range(0, rows_idx.size, rpc):
            sel = rows_idx[lo:lo + rpc]
            pad = rpc - sel.size
            d_off = doff[sel]
            d_len = dlen_v[sel]
            st = stored[sel]
            pv = prev[sel]
            if pad:  # zero rows + zero prev/stored: trivially true
                d_off = np.pad(d_off, (0, pad))
                d_len = np.pad(d_len, (0, pad))
                st = np.pad(st, (0, pad))
                pv = np.pad(pv, (0, pad))
            if native.available():
                rows = native.pad_rows(blob, d_off, d_len, w)
            else:
                rows = _pad_rows_numpy(blob, d_off, d_len, w)
            inject_seeds(rows, d_len, pv)
            _ledger.h2d(ledger_stage, rows)
            shipped = transport.ship(rows)
            with _ledger.dispatch(ledger_stage):
                handle = transport.verify(shipped, st)
            out.append((sel, sel.size, handle))
    _CHUNK_HIST["h2d"].observe(time.perf_counter() - t0)
    return out


def stream_scan_verify(blob: np.ndarray, *, seed: int = 0,
                       chunk_bytes: int | None = None,
                       route: str = "stream", transport=None,
                       byte_budget: int = 1 << 28, depth: int = 2,
                       ledger_stage: str = "replay.stream"):
    """Chunked streaming scan + rolling-chain verify of a WAL blob.

    Returns the whole stream's scan arrays ``(types, crcs, data_off,
    data_len, ent_index, ent_term, ent_type)`` — identical, bit for
    bit, to ``native.wal_scan(blob)`` with the chain verified — or
    raises the same typed errors the monolithic lanes raise.

    ``route="host"``: each chunk is one FUSED native sweep (frame +
    parse + CRC in a single pass, the Go baseline's shape); no device
    is touched.  ``route="stream"``: host framing of chunk k+1
    overlaps H2D of chunk k and device verify of chunk k-1; at most
    ``depth`` chunks are buffered on each seam (double buffering).
    ``transport`` injects the device legs for tests.
    """
    if not native.available():
        raise native.NativeError("native library unavailable")
    n = int(blob.size)
    if chunk_bytes is None:
        chunk_bytes = get_policy().chunk_bytes
    chunk_bytes = max(1, int(chunk_bytes))
    # ONE length-hop count sizes the whole stream's output arrays, so
    # every chunk sweep writes into its slice — no per-chunk
    # allocation, no final concatenate (the per-chunk tax that made
    # early chunked runs ~35% slower than the fused pass)
    try:
        total, _ = native.wal_count_range(blob, 0, n)
    except native.NativeError as e:
        _raise_native(e)
    full = native.alloc_scan_arrays(total)

    if route == "host":
        pos, base, chain = 0, 0, seed
        while pos < n:
            t0 = time.perf_counter()
            try:
                # one FUSED sweep per chunk; the ledger seam makes the
                # per-chunk cadence readable off /metrics even on the
                # no-device route (dispatches = chunks)
                with _ledger.dispatch(ledger_stage):
                    *arrays, nxt = native.scan_chunk(
                        blob, pos, chunk_bytes, seed=chain,
                        verify=True, out=full, out_base=base)
            except native.NativeError as e:
                _raise_native(e, base)
            _CHUNK_HIST["scan"].observe(time.perf_counter() - t0)
            cnt = arrays[0].size
            if cnt:
                chain = int(arrays[1][-1])
            base += cnt
            if nxt <= pos:  # defensive: no forward progress
                break
            pos = nxt
        return tuple(a[:base] for a in full)

    transport = transport or DeviceTransport()
    scan_q: Queue = Queue(maxsize=depth)
    cancel = threading.Event()
    scan_err: list[BaseException] = []

    def scanner():
        pos, base = 0, 0
        try:
            while pos < n:
                t0 = time.perf_counter()
                *arrays, nxt = native.scan_chunk(
                    blob, pos, chunk_bytes, verify=False,
                    out=full, out_base=base)
                _CHUNK_HIST["scan"].observe(time.perf_counter() - t0)
                _qput(scan_q, ("chunk", base, tuple(arrays)), cancel)
                base += arrays[0].size
                if nxt <= pos:
                    break
                pos = nxt
            _qput(scan_q, ("done", base, None), cancel)
        except _Cancelled:
            pass
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            scan_err.append(e)
            try:
                _qput(scan_q, ("err", 0, None), cancel)
            except _Cancelled:
                pass

    th = threading.Thread(target=scanner, daemon=True,
                          name="replay-stream-scan")
    th.start()
    inflight: deque = deque()
    prev_tail: int | None = None
    first_bad: int | None = None

    def collect_one():
        nonlocal first_bad
        base, crcs, handles = inflight.popleft()
        t0 = time.perf_counter()
        for sel, n_real, handle in handles:
            ok = transport.collect(handle)
            _ledger.d2h(ledger_stage, ok)
            if not ok.all():
                bad = base + int(sel[np.argmin(ok[:n_real])])
                if first_bad is None or bad < first_bad:
                    first_bad = bad
        _CHUNK_HIST["verify"].observe(time.perf_counter() - t0)
        if first_bad is not None:
            raise CRCMismatchError(
                f"crc chain broken at record {first_bad} "
                f"(stored={int(crcs[first_bad - base]):#x})")

    filled = 0
    try:
        while True:
            kind, base, arrays = scan_q.get()
            if kind == "err":
                e = scan_err[0]
                if isinstance(e, native.NativeError):
                    _raise_native(e, base)
                raise e
            if kind == "done":
                filled = base
                break
            types, crcs = arrays[0], arrays[1]
            if crcs.size == 0:
                continue
            if prev_tail is None:
                head = int(crcs[0]) if types[0] == CRC_TYPE else seed
            else:
                head = prev_tail
            prev = np.concatenate(
                [np.asarray([head], np.uint32), crcs[:-1]])
            handles = _dispatch_chunk_verify(
                blob, crcs, arrays[2], arrays[3], prev, transport,
                byte_budget, ledger_stage)
            inflight.append((base, crcs, handles))
            prev_tail = int(crcs[-1])
            while len(inflight) >= depth:
                collect_one()
        while inflight:
            collect_one()
    finally:
        cancel.set()
        _drain(scan_q)
        th.join(timeout=10)
    return tuple(a[:filled] for a in full)


class _Cancelled(Exception):
    pass


def _qput(q: Queue, item, cancel: threading.Event) -> None:
    while True:
        if cancel.is_set():
            raise _Cancelled()
        try:
            q.put(item, timeout=0.05)
            return
        except Full:
            continue


def _drain(q: Queue) -> None:
    while True:
        try:
            q.get_nowait()
        except Empty:
            return


def verify_chain_device(blob: np.ndarray, types, crcs, doff, dlen,
                        chunk_rows: int = 1 << 17,
                        byte_budget: int = 1 << 28) -> None:
    """Device-parallel rolling-chain verification of scanned records.

    Raises :class:`CRCMismatchError` naming the first bad record.
    A leading crcType record re-seeds the chain, mirroring the fresh-
    decoder rule of wal/wal.go:184-191 (a mid-file crc record instead
    participates as a regular zero-length link, which its stored value
    satisfies iff it matches the running chain — same check, batched).

    Each link i depends only on the *stored* value of link i-1, so
    verification is order-independent: records are grouped by width
    class (so one huge record cannot inflate every row) and processed
    in fixed-shape chunks (so each (width, rows) pair compiles once;
    short tails are padded with trivially-true links).
    """
    n = int(types.shape[0])
    if n == 0:
        return
    seed = 0
    start = 0
    if types[0] == CRC_TYPE:
        seed = int(crcs[0])
        start = 1
    if start >= n:
        return

    if native.available() and _accelerator_absent():
        # No accelerator: the batched bit-matmul CRC on JAX-CPU is
        # ~50x slower than one native core (VERDICT r4 #2 — the
        # framework must never lose to the reference on any backend).
        # CRC-only sweep over the spans the scan already produced
        # (decoder.go:28-47 chain semantics; no re-parse), naming the
        # first bad record exactly like the batched pass below.
        # Sharded across cores once the CRC work dwarfs thread
        # startup — each link needs only its predecessor's STORED
        # value, so record ranges verify independently.
        threads = 1
        if n - start >= (1 << 16):
            threads = min(os.cpu_count() or 1, 8)
        try:
            r = native.chain_verify(
                blob, doff[start:], dlen[start:], crcs[start:], seed,
                threads=threads)
        except native.NativeError as e:  # pragma: no cover - scan
            raise WALError(str(e)) from e  # guarantees spans in range
        if r == n - start:
            return
        bad = start + r
        raise CRCMismatchError(
            f"crc chain broken at record {bad} "
            f"(stored={int(crcs[bad]):#x})")

    from ..ops.crc_device import _chain_expected, raw_crc_batch

    stored = np.ascontiguousarray(crcs[start:], np.uint32)
    prev = np.concatenate(
        [np.asarray([seed], np.uint32), crcs[start:-1]])
    doff_v = doff[start:]
    dlen_v = np.ascontiguousarray(dlen[start:], np.uint64)

    wcls = np.where(
        dlen_v <= 2048,
        np.maximum(64, -(-dlen_v.astype(np.int64) // 128) * 128),
        np.int64(1) << np.ceil(
            np.log2(np.maximum(dlen_v, 1).astype(np.float64))
        ).astype(np.int64))

    first_bad = None
    for w in np.unique(wcls):
        w = int(w)
        rows_idx = np.nonzero(wcls == w)[0]
        # byte_budget caps host-chunk bytes even for multi-MiB width
        # classes (whose XLA bit expansion is ~8x the chunk size); the
        # floor is 1 row, never a fixed row count
        rpc = max(1, min(chunk_rows, byte_budget // w))
        # don't build a mostly-padding chunk for a tiny class; pow2
        # quantization keeps the compiled-shape count bounded
        rpc = min(rpc, max(8, 1 << (rows_idx.size - 1).bit_length()))
        for lo in range(0, rows_idx.size, rpc):
            sel = rows_idx[lo:lo + rpc]
            pad = rpc - sel.size
            d_off = doff_v[sel]
            d_len = dlen_v[sel]
            st = stored[sel]
            pv = prev[sel]
            if pad:  # zero-length/zero-crc links are trivially true
                d_off = np.pad(d_off, (0, pad))
                d_len = np.pad(d_len, (0, pad))
                st = np.pad(st, (0, pad))
                pv = np.pad(pv, (0, pad))
            if native.available():
                rows = native.pad_rows(blob, d_off, d_len, w)
            else:
                rows = _pad_rows_numpy(blob, d_off, d_len, w)
            # devledger seam: the padded batch is the H2D shipment,
            # the [rows] ok mask the D2H readback — per-chunk cost of
            # the replay lane, readable off /metrics after a restart
            _ledger.h2d("replay.verify", rows)
            with _ledger.dispatch("replay.verify"):
                ok = np.asarray(
                    _chain_expected(pv, raw_crc_batch(rows),
                                    d_len.astype(np.uint32)) == st)
            _ledger.d2h("replay.verify", ok)
            if not ok.all():
                bad = start + int(sel[np.argmin(ok[:sel.size])])
                if first_bad is None or bad < first_bad:
                    first_bad = bad
    if first_bad is not None:
        raise CRCMismatchError(
            f"crc chain broken at record {first_bad} "
            f"(stored={int(crcs[first_bad]):#x})")


def read_all_device(dirpath: str, index: int = 0,
                    route: str | None = None
                    ) -> tuple[bytes | None, HardState, EntryBlock]:
    """Batched-replay equivalent of ``WAL.open_at_index + read_all``.

    Same semantics as the host path (metadata conflict, state
    selection, entry dedup-by-index, index-gap and not-found errors)
    with the scan/verify lane chosen by ``route`` — ``host`` (one
    fused native sweep), ``device`` (monolithic batched verify),
    ``stream`` (the chunked overlap pipeline) — or, when None, by the
    measured backend router (wal/backend_policy).  Returns entries as
    an :class:`EntryBlock`; the WAL object itself is NOT opened for
    append (use ``WAL.open_at_index`` for the read-then-append
    lifecycle — this path is the bulk-replay fast lane).
    """
    names = select_segments(dirpath, index)
    blobs = [np.fromfile(os.path.join(dirpath, nm), dtype=np.uint8)
             for nm in names]
    blob = np.concatenate(blobs) if len(blobs) > 1 else blobs[0]

    verified = False
    if native.available():
        if route is None:
            route = get_policy().route("replay",
                                       size_bytes=int(blob.size))
        try:
            if route == "host":
                # the Go baseline's fused shape: frame + parse + CRC
                # in ONE pass over the blob — no chain_verify re-read
                types, crcs, doff, dlen, eidx, eterm, etype = \
                    native.scan_verify(blob)
                verified = True
            elif route == "stream":
                types, crcs, doff, dlen, eidx, eterm, etype = \
                    stream_scan_verify(blob, route="stream")
                verified = True
            else:
                types, crcs, doff, dlen, eidx, eterm, etype = \
                    native.wal_scan(blob)
        except native.NativeError as e:
            # error-type parity with the host path: WAL corruption is
            # a WALError regardless of which scanner found it, and a
            # stream that ends mid-record is the same typed
            # TornTailError the host decoder raises (mapped by native
            # return code, never message text)
            _raise_native(e)
    else:
        try:
            types, crcs, doff, dlen, eidx, eterm, etype = \
                _scan_python(blob)
        except ProtoError as e:  # same parity for the python scanner
            raise WALError(str(e)) from e

    known = np.isin(types, (METADATA_TYPE, ENTRY_TYPE, STATE_TYPE,
                            CRC_TYPE))
    if not known.all():
        j = int(np.argmin(known))
        raise WALError(f"unexpected block type {int(types[j])}")

    if not verified:
        verify_chain_device(blob, types, crcs, doff, dlen)

    # -- host semantics over the scan arrays --------------------------------
    metadata: bytes | None = None
    for j in np.nonzero(types == METADATA_TYPE)[0]:
        md = blob[int(doff[j]):int(doff[j]) + int(dlen[j])].tobytes()
        if metadata is not None and metadata != md:
            raise MetadataConflictError()
        metadata = md

    state = HardState()
    st_idx = np.nonzero(types == STATE_TYPE)[0]
    if st_idx.size:
        j = int(st_idx[-1])
        state = HardState.unmarshal(
            blob[int(doff[j]):int(doff[j]) + int(dlen[j])].tobytes())

    # Entry selection mirrors the host read_all loop exactly
    # (wal.py read_all / reference wal/wal.go:171-175): ri = the open
    # index, keep entries with e.index >= ri, dedup-by-index with
    # tail truncation, and the final last-entry >= ri check.
    ei = np.nonzero(types == ENTRY_TYPE)[0]
    ri = index
    if ei.size:
        idxs = eidx[ei].astype(np.int64)
        keep = idxs >= ri
        ei_k = ei[keep]
        idxs_k = idxs[keep]
        if idxs_k.size and np.all(np.diff(idxs_k) == 1) \
                and idxs_k[0] == ri:
            sel = ei_k  # fast path: consecutive from ri, no overwrites
        else:
            # crash-overwrite / gap path: replay dedup-by-index
            kept: list[int] = []
            for j, idx in zip(ei_k, idxs_k):
                slot = int(idx) - ri
                if slot > len(kept):
                    raise WALError(
                        f"entry index gap: {int(idx)} after "
                        f"{len(kept)} entries from {ri}")
                del kept[slot:]
                kept.append(int(j))
            sel = np.asarray(kept, np.int64)
        enti = int(eidx[ei[-1]])  # last entry index SEEN (host parity)
    else:
        sel = np.asarray([], np.int64)
        enti = 0

    if enti < ri:
        raise IndexNotFoundError(f"last entry {enti} < requested {ri}")

    block = EntryBlock(
        index=eidx[sel], term=eterm[sel], type=etype[sel],
        data_off=doff[sel], data_len=dlen[sel], blob=blob,
        last_crc=int(crcs[-1]) if crcs.size else 0)
    return metadata, state, block


def open_replay_device(dirpath: str, index: int = 0,
                       route: str | None = None
                       ) -> tuple[WAL, bytes | None, HardState, EntryBlock]:
    """Replay on the routed fast lane, then open the WAL for appends.

    The device-backed equivalent of ``open_at_index + read_all``: the
    batched pass both verifies the stream and yields the chain tail
    CRC, so the append encoder seeds directly (WAL.open_at_end) with
    no sequential re-read.
    """
    metadata, state, block = read_all_device(dirpath, index, route)
    enti = int(block.index[-1]) if len(block) else 0
    w = WAL.open_at_end(dirpath, metadata, block.last_crc, enti)
    return w, metadata, state, block
