"""Write-ahead log: append-only segmented record log with rolling CRC.

Host-path port of the reference's wal package semantics
(wal/wal.go:57-293): a WAL is either in read mode or append mode; a
newly created WAL appends, a just-opened WAL reads, and becomes
appendable only after ``read_all`` drains it.  Files are named
``%016x-%016x.wal`` (seq, index) (wal/util.go:86-88); each file starts
with a crcType record carrying the rolling CRC at the cut point
(wal/wal.go:93,234) followed by a metadata record, so segments chain.

Record framing (wal/encoder.go:25-37, decoder.go:28-47): little-endian
int64 length prefix, then the marshaled walpb Record.  The rolling
digest covers record *data* only — the framing and record envelope are
protected by the fact that a corrupted envelope fails to unmarshal.

The batched device replay lives in ``replay.py``; this module is the
durable read/write seam shared by both paths.
"""

from __future__ import annotations

import errno
import logging
import os
import struct
import time
from typing import BinaryIO

from ..crc import Digest
from ..obs import metrics as _obs
from ..utils import faults as _faults
from ..utils.errors import EtcdNoSpace
from ..utils.fsio import fsync as fsio_fsync, fsync_dir
from ..wire import Entry, HardState, Record
from .errors import (
    CRCMismatchError,
    FileNotFoundError_,
    IndexNotFoundError,
    MetadataConflictError,
    TornTailError,
    WALError,
)

log = logging.getLogger(__name__)

# record types (reference wal/wal.go:35-39)
METADATA_TYPE = 1
ENTRY_TYPE = 2
STATE_TYPE = 3
CRC_TYPE = 4

_PRIVATE_DIR_MODE = 0o700
_LEN_STRUCT = struct.Struct("<q")

# obs seams (PR 2): fsync latency is THE durability hot metric — every
# client ack sits behind one of these (the Ready contract)
_FSYNC_HIST = _obs.registry.histogram("etcd_wal_fsync_seconds")
_APPEND_CTR = _obs.registry.counter("etcd_wal_append_entries_total")
_CUT_CTR = _obs.registry.counter("etcd_wal_cuts_total")
_GC_CTR = _obs.registry.counter("etcd_wal_segments_gc_total")


def wal_name(seq: int, index: int) -> str:
    return f"{seq:016x}-{index:016x}.wal"


def parse_wal_name(name: str) -> tuple[int, int]:
    """Raises ValueError on non-WAL names (reference wal/util.go:77-84)."""
    if not name.endswith(".wal"):
        raise ValueError(f"bad wal name: {name}")
    stem = name[:-4]
    seq_s, _, index_s = stem.partition("-")
    if len(seq_s) != 16 or len(index_s) != 16:
        raise ValueError(f"bad wal name: {name}")
    return int(seq_s, 16), int(index_s, 16)


def check_wal_names(names: list[str]) -> list[str]:
    out = []
    for name in names:
        try:
            parse_wal_name(name)
        except ValueError:
            continue
        out.append(name)
    return out


def search_index(names: list[str], index: int) -> int | None:
    """Last position whose raft-index section is <= index; names sorted
    (reference wal/util.go:20-32)."""
    for i in range(len(names) - 1, -1, -1):
        _, cur_index = parse_wal_name(names[i])
        if index >= cur_index:
            return i
    return None


def is_valid_seq(names: list[str]) -> bool:
    """Sequence numbers must increase continuously (wal/util.go:36-49)."""
    last_seq = 0
    for name in names:
        cur_seq, _ = parse_wal_name(name)
        if last_seq != 0 and last_seq != cur_seq - 1:
            return False
        last_seq = cur_seq
    return True


def select_segments(dirpath: str, index: int) -> list[str]:
    """Sorted, seq-contiguous segment names whose chain covers
    ``index`` — the shared restart seam behind ``open_at_index`` and
    the device/streaming replay lanes (both must agree on which files
    constitute the stream, or the two paths could replay different
    bytes from the same directory)."""
    try:
        names = os.listdir(dirpath)
    except OSError as e:
        raise FileNotFoundError_(str(e)) from e
    names = sorted(check_wal_names(names))
    if not names:
        raise FileNotFoundError_(dirpath)
    i = search_index(names, index)
    if i is None or not is_valid_seq(names[i:]):
        raise FileNotFoundError_(f"no wal file covers index {index}")
    return names[i:]


def exist(dirpath: str) -> bool:
    try:
        return len(os.listdir(dirpath)) != 0
    except OSError:
        return False


def _open_append_0600(path: str) -> BinaryIO:
    """Segment files carry owner-only mode like the reference
    (wal/wal.go:82,222 pass 0600)."""
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o600)
    return os.fdopen(fd, "ab")


class _Encoder:
    """Rolling-CRC record encoder (reference wal/encoder.go:13-45)."""

    def __init__(self, f: BinaryIO, prev_crc: int):
        self.f = f
        self.crc = Digest(prev_crc)

    def encode(self, rec: Record) -> None:
        if rec.data is not None:
            self.crc.write(rec.data)
        rec.crc = self.crc.sum32()
        data = rec.marshal()
        self.f.write(_LEN_STRUCT.pack(len(data)))
        self.f.write(data)


class _Decoder:
    """Sequential record decoder over a chain of segment files
    (reference wal/decoder.go:14-59 + MultiReadCloser)."""

    def __init__(self, files: list[BinaryIO]):
        self.files = files
        self.fi = 0
        self.crc = Digest(0)
        # (file index, offset) where the NEXT record starts — the
        # truncation point for torn-tail repair
        self.good = (0, 0)

    def _read(self, n: int) -> bytes:
        """ReadFull across the file chain; b'' at a clean stream end."""
        chunks = []
        need = n
        while need > 0:
            if self.fi >= len(self.files):
                break
            chunk = self.files[self.fi].read(need)
            if not chunk:
                self.fi += 1
                continue
            chunks.append(chunk)
            need -= len(chunk)
        return b"".join(chunks)

    def decode(self) -> Record | None:
        """Next record, or None at a clean EOF.  A partial trailing
        record raises (the reference surfaces io.ErrUnexpectedEOF)."""
        # advance past exhausted files so the recorded record-start
        # position is meaningful for repair
        while self.fi < len(self.files):
            probe = self.files[self.fi].read(1)
            if probe:
                self.files[self.fi].seek(-1, 1)
                break
            self.fi += 1
        if self.fi >= len(self.files):
            return None
        self.good = (self.fi, self.files[self.fi].tell())
        header = self._read(8)
        if len(header) < 8:
            raise TornTailError("unexpected EOF in record length")
        (length,) = _LEN_STRUCT.unpack(header)
        if length < 0:
            raise WALError(f"negative record length {length}")
        data = self._read(length)
        if len(data) < length:
            raise TornTailError("unexpected EOF in record body")
        rec = Record.unmarshal(data)
        # skip crc checking if the record type is crcType
        # (wal/decoder.go:41-43)
        if rec.type == CRC_TYPE:
            return rec
        if rec.data is not None:
            self.crc.write(rec.data)
        if rec.crc != self.crc.sum32():
            raise CRCMismatchError(
                f"crc mismatch: record={rec.crc:#x} "
                f"computed={self.crc.sum32():#x}")
        return rec

    def update_crc(self, prev_crc: int) -> None:
        self.crc = Digest(prev_crc)

    def last_crc(self) -> int:
        return self.crc.sum32()

    def close(self) -> None:
        for f in self.files:
            f.close()


class WAL:
    """Logical representation of the stable storage (wal/wal.go:57-68)."""

    def __init__(self) -> None:
        self.dir = ""
        self.md: bytes | None = None
        self.ri = 0  # index of entry to start reading
        self.decoder: _Decoder | None = None
        self.f: BinaryIO | None = None  # file opened for appending
        self.seq = 0
        self.enti = 0  # index of the last entry saved
        self.encoder: _Encoder | None = None
        # path of the append-mode segment (fdopen'd handles carry no
        # usable .name — the ENOSPC rollback reopens by path)
        self._fpath = ""

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, dirpath: str, metadata: bytes) -> "WAL":
        """Create an append-mode WAL; metadata heads every segment
        (reference wal/wal.go:72-100)."""
        if exist(dirpath):
            raise FileExistsError(dirpath)
        os.makedirs(dirpath, mode=_PRIVATE_DIR_MODE, exist_ok=True)
        p = os.path.join(dirpath, wal_name(0, 0))
        f = _open_append_0600(p)
        w = cls()
        w.dir = dirpath
        w.md = metadata
        w.seq = 0
        w.f = f
        w._fpath = p
        w.encoder = _Encoder(f, 0)
        w._save_crc(0)
        w.encoder.encode(Record(type=METADATA_TYPE, data=metadata))
        # the header records and the segment's directory entry must
        # be durable before the WAL is handed out — a crash between
        # create() and the first save() must not lose the metadata
        # record that every later open validates against
        w.sync()
        fsync_dir(dirpath)
        return w

    @classmethod
    def open_at_end(cls, dirpath: str, metadata: bytes | None,
                    last_crc: int, enti: int) -> "WAL":
        """Open directly in append mode, seeding the encoder's rolling
        CRC with ``last_crc`` (the stored CRC of the final record).

        Companion to the device replay path (replay_device.py), which
        verifies and decodes the whole stream in one batched pass and
        already knows the chain tail — so the read-then-append
        lifecycle of ``open_at_index`` + ``read_all`` is unnecessary.
        """
        names = sorted(check_wal_names(os.listdir(dirpath)))
        if not names:
            raise FileNotFoundError_(dirpath)
        seq, _ = parse_wal_name(names[-1])
        p = os.path.join(dirpath, names[-1])
        f = _open_append_0600(p)
        w = cls()
        w.dir = dirpath
        w.md = metadata
        w.seq = seq
        w.f = f
        w._fpath = p
        w.enti = enti
        w.encoder = _Encoder(f, last_crc)
        return w

    @classmethod
    def open_at_index(cls, dirpath: str, index: int) -> "WAL":
        """Open read-mode at ``index``; the caller must ``read_all``
        before appending (reference wal/wal.go:108-159)."""
        names = select_segments(dirpath, index)
        files = [open(os.path.join(dirpath, n), "rb")
                 for n in names]
        seq, _ = parse_wal_name(names[-1])
        p = os.path.join(dirpath, names[-1])
        f = open(p, "ab")

        w = cls()
        w.dir = dirpath
        w.ri = index
        w.decoder = _Decoder(files)
        w.f = f
        w._fpath = p
        w.seq = seq
        return w

    # -- read --------------------------------------------------------------

    def read_all(self, repair: bool = False
                 ) -> tuple[bytes | None, HardState, list[Entry]]:
        """Drain the WAL; afterwards it accepts appends
        (reference wal/wal.go:164-216).

        ``repair=True`` tolerates a TORN TAIL — a final record cut
        mid-write by a crash (unexpected EOF): the stream is
        truncated at the last complete record and replay succeeds
        with what is durable.  Safe because acks only follow fsync,
        so torn bytes were never acknowledged to anyone.  The
        reference's 0.5 snapshot log.Fatals here (server.go:156);
        later etcd grew exactly this repair.  Default False keeps the
        strict parity behavior (corruption detection tests).  Any
        OTHER corruption — CRC mismatch, index gap, a torn record
        followed by more data — still raises."""
        if self.decoder is None:
            raise WALError("wal not in read mode")
        metadata: bytes | None = None
        state = HardState()
        ents: list[Entry] = []

        repaired = False

        def decode_or_repair():
            nonlocal repaired
            try:
                return self.decoder.decode()
            except TornTailError as e:
                # torn tail: the failing record is by construction the
                # stream's last bytes (the chain is exhausted
                # mid-record), so every byte from the record start to
                # the end of the chain is part of the torn record —
                # truncate the file it starts in AND remove any later
                # files its bytes spilled into (unreachable from a
                # single crash since writes never span segments, but
                # repair exists for arbitrary crash states)
                if repair:
                    fi, off = self.decoder.good
                    if off == 0 and fi == 0:
                        # the tear consumes the very head of the
                        # decoder's first file: nothing in the read
                        # window is salvageable, and truncating would
                        # manufacture a headless zero-byte segment
                        # (no CRC/metadata records — mid-chain opens
                        # would then corrupt the CRC chain, full
                        # opens would lose node metadata).  Refuse:
                        # nothing here was ever synced+acked.
                        raise
                    if off == 0 and fi > 0:
                        # the tear starts at byte 0 of segment fi:
                        # truncating would leave a headless segment
                        # (no CRC/metadata records) that a later
                        # mid-chain open would reject — the chain
                        # ended exactly at fi-1's end, so fi itself
                        # is all torn bytes; drop it too
                        fi, off = fi - 1, None
                    path = self.decoder.files[fi].name
                    if off is not None:
                        os.truncate(path, off)
                        # the truncation itself must be durable
                        # before replay returns: a crash after a
                        # repaired-but-unsynced truncate would
                        # resurrect the torn bytes on the next open
                        # (fsio.fsync seam: EIO here is fail-stop)
                        tfd = os.open(path, os.O_RDONLY)
                        try:
                            fsio_fsync(tfd)
                        finally:
                            os.close(tfd)
                    doomed = self.decoder.files[fi + 1:]
                    # REMOVE, don't truncate-to-zero: a zero-length
                    # segment carries no metadata/CRC head record and
                    # would break any per-file validation on a later
                    # open (advisor r4).  Descending order with a
                    # directory fsync after EACH unlink keeps any
                    # crash-surviving subset seq-contiguous — without
                    # the per-remove fsync the journal may persist
                    # the unlinks out of call order, stranding a gap
                    # that bricks every subsequent open.
                    if doomed:
                        dfd = os.open(self.dir, os.O_RDONLY)
                        try:
                            for lf in reversed(doomed):
                                os.remove(lf.name)
                                os.fsync(dfd)
                        finally:
                            os.close(dfd)
                        # appends must continue in the surviving
                        # segment — self.f was opened on the last
                        # (now removed) file
                        self.f.close()
                        self.f = _open_append_0600(path)
                        self._fpath = path
                        self.seq, _ = parse_wal_name(
                            os.path.basename(path))
                    fsync_dir(self.dir)
                    log.warning(
                        "wal: repaired torn tail: kept %s%s, removed "
                        "%d later file(s) (%s)",
                        os.path.basename(path),
                        "" if off is None else f" (cut at byte {off})",
                        len(doomed), e)
                    repaired = True
                    return None
                raise

        while (rec := decode_or_repair()) is not None:
            if rec.type == ENTRY_TYPE:
                e = Entry.unmarshal(rec.data or b"")
                if e.index >= self.ri:
                    # dedup-by-index: an uncommitted tail may be
                    # overwritten after restart (wal/wal.go:171-175);
                    # a gap would slice out of range in the reference
                    if e.index - self.ri > len(ents):
                        raise WALError(
                            f"entry index gap: {e.index} after "
                            f"{len(ents)} entries from {self.ri}")
                    del ents[e.index - self.ri:]
                    ents.append(e)
                self.enti = e.index
            elif rec.type == STATE_TYPE:
                state = HardState.unmarshal(rec.data or b"")
            elif rec.type == METADATA_TYPE:
                if metadata is not None and metadata != rec.data:
                    raise MetadataConflictError()
                metadata = rec.data
            elif rec.type == CRC_TYPE:
                crc = self.decoder.crc.sum32()
                # a zero running crc means a fresh decoder (file head);
                # otherwise the chain must match (wal/wal.go:184-191)
                if crc != 0 and rec.crc != crc:
                    raise CRCMismatchError(
                        f"segment boundary crc: record={rec.crc:#x} "
                        f"running={crc:#x}")
                self.decoder.update_crc(rec.crc)
            else:
                raise WALError(f"unexpected block type {rec.type}")

        if self.enti < self.ri:
            raise IndexNotFoundError(
                f"last entry {self.enti} < requested {self.ri}")

        if repaired and state.commit > self.enti:
            # WALs written before the entries-before-state order (or
            # a tear inside the entry run) can leave a surviving
            # state record whose commit points past the surviving
            # entries; an unclamped commit makes the restarted node
            # skip its whole apply window (a silent zombie).  The
            # torn suffix was never acked, so clamping is safe.
            log.warning("wal: repaired tail — clamping commit %d to "
                        "last surviving entry %d", state.commit,
                        self.enti)
            state = HardState(term=state.term, vote=state.vote,
                              commit=self.enti)

        # close decoder, disable reading; chain the encoder's crc
        last_crc = self.decoder.last_crc()
        self.decoder.close()
        self.decoder = None
        self.ri = 0
        self.md = metadata
        self.encoder = _Encoder(self.f, last_crc)
        return metadata, state, ents

    # -- append ------------------------------------------------------------

    def cut(self) -> None:
        """Close the current segment and start seq+1 at enti+1
        (reference wal/wal.go:219-238)."""
        if self.encoder is None:
            raise WALError("wal not in append mode")
        try:
            _faults.hit("wal.cut")
        except OSError as e:
            if e.errno == errno.ENOSPC:
                raise EtcdNoSpace(cause=f"wal cut: {e}") from e
            raise
        fpath = os.path.join(self.dir, wal_name(self.seq + 1, self.enti + 1))
        f = _open_append_0600(fpath)
        self.sync()
        self.f.close()

        self.f = f
        self._fpath = fpath
        self.seq += 1
        prev_crc = self.encoder.crc.sum32()
        self.encoder = _Encoder(self.f, prev_crc)
        self._save_crc(prev_crc)
        self.encoder.encode(Record(type=METADATA_TYPE, data=self.md))
        # new segment's header records + directory entry durable
        # before any entry lands in it: a crash after cut() but
        # before the next save() must leave an openable chain
        self.sync()
        fsync_dir(self.dir)
        _CUT_CTR.inc()

    def gc(self, index: int) -> int:
        """Delete segment files wholly behind ``index`` — the durable
        snapshot index (PR 6 segment GC; the reference's
        wal.ReleaseLockTo boundary).  Returns how many were removed.

        The segment CONTAINING ``index`` is always kept: restart
        replays from the snapshot index via ``select_segments``,
        which needs a file whose start is <= index.  CALLER CONTRACT:
        the snapshot superseding the deleted entries must already be
        durable (file + dir fsync) — the snapshotter's ``_save`` does
        exactly that before returning, and the durability checker's
        unsynced-delete rule guards the ordering inside this module.

        Crash-safe at any prefix: removal runs OLDEST-FIRST with a
        directory fsync after EACH unlink, so any crash-surviving
        subset is a seq-contiguous suffix still covering ``index``
        (the same per-remove discipline as the torn-tail repair,
        mirrored — that one removes newest-first to keep a contiguous
        PREFIX)."""
        _faults.hit("wal.gc")
        names = sorted(check_wal_names(os.listdir(self.dir)))
        i = search_index(names, index)
        if not i:  # None (index below the chain) or 0: nothing behind
            return 0
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            for name in names[:i]:
                os.remove(os.path.join(self.dir, name))
                os.fsync(dfd)
        finally:
            os.close(dfd)
        _GC_CTR.inc(i)
        log.info("wal: gc removed %d segment(s) behind index %d "
                 "(kept %s..)", i, index, names[i])
        return i

    def sync(self) -> None:
        """flush + fsync the append segment.  Failure semantics
        (PR 10): ENOSPC raises the typed ``EtcdNoSpace`` (``save``
        rolls the file back to the pre-batch mark and the server
        enters read-only NOSPACE mode); ANY other fsync error is
        FAIL-STOP — after one failed fsync the kernel may have
        dropped the dirty pages while a retry reports success, so a
        server that retried could ack writes that no longer exist
        (the silent-loss class etcd grew panic-on-fsync-error for).
        This method either returns with the bytes durable, raises
        EtcdNoSpace with the file unchanged on disk semantics, or
        the process is down."""
        if self.f is None:
            return
        t0 = time.perf_counter()
        try:
            _faults.hit("wal.fsync")
            self.f.flush()
            os.fsync(self.f.fileno())
        except OSError as e:
            if e.errno == errno.ENOSPC:
                raise EtcdNoSpace(cause=f"wal fsync: {e}") from e
            _faults.fail_stop(
                f"wal fsync failed on {self._fpath}: {e} — a "
                f"server that retries fsync may silently lose "
                f"acked writes", e)
        _FSYNC_HIST.observe(time.perf_counter() - t0)

    def probe_space(self) -> None:
        """NOSPACE recovery probe: exercise the append + fsync seams
        without writing any record.  Raises ``EtcdNoSpace`` while
        the disk (or an armed ``enospc`` failpoint window) still
        refuses; returns cleanly once space is back so the server
        can leave read-only mode."""
        if self.f is None:
            raise WALError("wal closed")
        try:
            _faults.hit("wal.append")
            _faults.hit("wal.fsync")
            self.f.flush()
            os.fsync(self.f.fileno())
        except OSError as e:
            if e.errno == errno.ENOSPC:
                raise EtcdNoSpace(cause=f"nospace probe: {e}") from e
            _faults.fail_stop(
                f"wal probe fsync failed on {self._fpath}: {e}", e)

    def close(self) -> None:
        if self.decoder is not None:
            self.decoder.close()
            self.decoder = None
        if self.f is not None:
            if self.encoder is not None:
                try:
                    self.sync()
                except EtcdNoSpace:
                    # best-effort final sync on a full disk: every
                    # acked write was already fsynced by its save();
                    # anything buffered here was never acked
                    log.warning("wal: close() sync skipped (ENOSPC)")
            self.f.close()
            self.f = None

    def save_entry(self, e: Entry) -> None:
        if self.encoder is None:
            raise WALError("wal not in append mode (read_all first)")
        rec = Record(type=ENTRY_TYPE, data=e.marshal())
        self.encoder.encode(rec)
        self.enti = e.index

    def save_state(self, st: HardState) -> None:
        from ..wire import is_empty_hard_state

        if is_empty_hard_state(st):
            return
        if self.encoder is None:
            raise WALError("wal not in append mode (read_all first)")
        self.encoder.encode(Record(type=STATE_TYPE, data=st.marshal()))

    def save(self, st: HardState, ents: list[Entry]) -> None:
        """HardState + entries + fsync — the Ready-contract durability
        step (reference wal/wal.go:281-288, state record first for
        byte-layout parity; read_all's repair clamp covers the
        state-before-entries tear case).

        ENOSPC anywhere in the batch (write, flush, or fsync) rolls
        the segment back to the pre-batch mark — truncate below any
        bytes whose writeback the kernel may have dropped, fsync the
        truncation — and raises the typed ``EtcdNoSpace``: the WAL
        stays append-usable, nothing in the failed batch was ever
        acked, and everything before the mark was already durable
        from the previous save.  Any OTHER I/O error is fail-stop
        (see :meth:`sync`)."""
        if self.encoder is None:
            raise WALError("wal not in append mode (read_all first)")
        mark = (self.f.tell(), self.encoder.crc.sum32(), self.enti)
        try:
            _faults.hit("wal.append")
            self.save_state(st)
            for e in ents:
                self.save_entry(e)
        except OSError as e:
            if e.errno == errno.ENOSPC:
                self._rollback(mark, e)  # raises EtcdNoSpace
            _faults.fail_stop(
                f"wal append failed on {self._fpath}: {e}", e)
        if ents:
            _APPEND_CTR.inc(len(ents))
        try:
            self.sync()
        except EtcdNoSpace as e:
            self._rollback(mark, e)
            raise  # unreachable — _rollback always raises; keeps
            #        the no-return-without-fsync path explicit

    def _rollback(self, mark: tuple[int, int, int], cause) -> None:
        """Revert the append segment to the pre-batch ``mark`` after
        an ENOSPC: reopen (dropping any unflushable buffer),
        truncate to the mark (discarding bytes whose writeback may
        already have been dropped — they were never acked), fsync
        the truncation, and rebuild the encoder on the pre-batch
        rolling CRC.  Raises ``EtcdNoSpace``; if even the rollback
        fails the only honest state is fail-stop."""
        off, crc, enti = mark
        try:
            try:
                self.f.close()  # flush may re-raise ENOSPC: ignore
            except OSError:
                pass
            os.truncate(self._fpath, off)
            tfd = os.open(self._fpath, os.O_RDONLY)
            try:
                os.fsync(tfd)
            finally:
                os.close(tfd)
            self.f = _open_append_0600(self._fpath)
            self.encoder = _Encoder(self.f, crc)
            self.enti = enti
        except OSError as e:
            _faults.fail_stop(
                f"wal ENOSPC rollback failed on {self._fpath}: {e} "
                f"(original: {cause})", e)
        log.warning("wal: ENOSPC — rolled %s back to byte %d (%s)",
                    os.path.basename(self._fpath), off, cause)
        if isinstance(cause, EtcdNoSpace):
            raise cause
        raise EtcdNoSpace(cause=f"wal save: {cause}") from (
            cause if isinstance(cause, BaseException) else None)

    def _save_crc(self, prev_crc: int) -> None:
        self.encoder.encode(Record(type=CRC_TYPE, crc=prev_crc))
