"""L3* snapshotter: CRC-protected raft snapshot files.

Reference snap/snapshotter.go.  The whole-file CRC is the device-hash
target for large store snapshots (bench config 3); ``Snapshotter``
accepts a pluggable ``crc_fn`` so the device kernel slots in behind the
same seam.  ``stream`` (PR 6) adds the chunked, rolling-CRC-verified
snapshot transfer the dist tier's deep-lag catch-up rides.
"""

from .snapshotter import (
    DEFAULT_SNAP_KEEP,
    NoSnapshotError,
    SnapCRCMismatchError,
    SnapEmptyError,
    Snapshotter,
)

__all__ = [
    "DEFAULT_SNAP_KEEP",
    "Snapshotter",
    "NoSnapshotError",
    "SnapCRCMismatchError",
    "SnapEmptyError",
]
