"""L3* snapshotter: CRC-protected raft snapshot files.

Reference snap/snapshotter.go.  The whole-file CRC is the device-hash
target for large store snapshots (bench config 3); ``Snapshotter``
accepts a pluggable ``crc_fn`` so the device kernel slots in behind the
same seam.
"""

from .snapshotter import SnapEmptyError, Snapshotter, SnapCRCMismatchError, NoSnapshotError

__all__ = [
    "Snapshotter",
    "NoSnapshotError",
    "SnapCRCMismatchError",
    "SnapEmptyError",
]
