"""Streamed snapshot transfer (PR 6): chunked, verified, resumable.

The dist tier's original catch-up path pulled the donor's whole store
as ONE blocking, unverified JSON blob (``GET /mraft/snapshot``) — a
deep-lag peer re-pulling a multi-hundred-MB snapshot after every
transport hiccup, with no integrity check at all.  This module is the
snapshot analog of PR 3's streaming replay lane:

- **Donor side** (:class:`SnapshotSource` + :class:`SourceCache`):
  the serialized snapshot blob is PINNED under a unique id and served
  in fixed-size chunks, each carrying a rolling CRC32C chained across
  chunks (the WAL's seedable-digest chain, pkg/crc/crc.go:23, applied
  to the snapshot byte stream).  Pinning matters because the live
  store mutates continuously — chunk k and chunk k+1 must come from
  the SAME serialization or the assembled blob is garbage.
- **Receiver side** (:class:`ChunkPuller`): chunk requests ride a
  ``peerlink.PipeChannel`` with a window of requests in flight
  (network fetch of chunk k+w overlaps verification of chunk k); a
  corrupt chunk is rejected and refetched (never installed), a
  transport failure resumes from the last verified chunk over the
  channel's automatic reconnect, and a donor that dropped the pin
  answers 404 → the puller aborts with :class:`StaleSourceError` so
  the caller refetches meta and restarts against a fresh pin.
- **Verification** (:class:`ChunkVerifier`) routes like the replay
  lane: host seedable digest when no accelerator is present, the
  GF(2) seed-stitched device form (ops/crc_device.inject_seeds →
  one raw-CRC matmul + compare) when there is one — chunk c seeds
  from chunk c-1's STORED value, the same induction the streaming
  replay chain uses, so install verifies at replay speed.

Nothing here persists partial state: the assembled blob exists only
in memory until the caller's install commits, so a receiver crash
mid-stream restarts cleanly with no artifact to discard.
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time

import numpy as np

from ..crc import update as crc_update
from ..obs import metrics as _obs
from ..utils import faults as _faults

log = logging.getLogger(__name__)

#: chunk size of the snapshot stream; 256 KiB keeps per-chunk verify
#: latency small against the fetch (loopback) while bounding the
#: request count for multi-GB stores.  ETCD_SNAP_CHUNK_BYTES
#: overrides at pin time (read per SnapshotSource so tests and
#: drills can tune it without re-importing).
DEFAULT_CHUNK_BYTES = 256 * 1024


def _default_chunk_bytes() -> int:
    return int(os.environ.get("ETCD_SNAP_CHUNK_BYTES",
                              DEFAULT_CHUNK_BYTES))

#: peer-handler paths (the dist server mounts meta/chunk as POST and
#: the frontier probe as GET)
META_PATH = "/mraft/snapshot/meta"
CHUNK_PATH = "/mraft/snapshot/chunk"
#: cheap pre-pin dominance probe: the donor's applied vector alone.
#: A meta pin serializes + CRC-chains the donor's whole store under
#: its lock and holds the blob pinned for the cache TTL — receivers
#: must never pay that for a donor that cannot dominate them.
FRONTIER_PATH = "/mraft/snapshot/frontier"

_CHUNK_HIST = _obs.registry.histogram("etcd_snap_stream_chunk_seconds")


def _install_ctr(outcome: str):
    return _obs.registry.counter("etcd_snap_install_total",
                                 outcome=outcome)


class SnapStreamError(Exception):
    """The chunk stream failed (transport, corruption budget,
    deadline); the caller may retry against this or another donor."""


class StaleSourceError(SnapStreamError):
    """The donor no longer pins this source id (restart or cache
    eviction): refetch meta and restart from a fresh pin."""


def chunk_crcs(payload: bytes, chunk_bytes: int) -> list[int]:
    """Rolling CRC32C chain over ``payload`` in ``chunk_bytes`` steps:
    ``crcs[k] = update(crcs[k-1], chunk_k)`` seeded from 0 — the WAL
    record chain's exact form, so the GF(2) seed-injection verifier
    applies unchanged."""
    out = []
    prev = 0
    for off in range(0, len(payload), chunk_bytes):
        prev = crc_update(prev, payload[off:off + chunk_bytes])
        out.append(prev)
    return out


class SnapshotSource:
    """One pinned, chunkable snapshot byte stream (donor side)."""

    _ids = itertools.count(1)

    def __init__(self, payload: bytes, extra: dict | None = None,
                 chunk_bytes: int | None = None):
        self.payload = payload
        self.chunk_bytes = int(chunk_bytes or _default_chunk_bytes())
        if self.chunk_bytes < 1:
            raise ValueError("chunk_bytes must be >= 1")
        # unique across donor restarts: a rebooted donor must never
        # serve a NEW pin's bytes against an OLD pin's chunk chain
        self.id = (f"{os.getpid():x}.{int(time.time() * 1e3):x}"
                   f".{next(self._ids)}")
        self.extra = dict(extra or {})
        self.crcs = chunk_crcs(payload, self.chunk_bytes)
        self.pinned_at = time.monotonic()

    @property
    def n_chunks(self) -> int:
        return len(self.crcs)

    def meta(self) -> dict:
        """The stream header the receiver plans the pull from."""
        return {
            "id": self.id,
            "size": len(self.payload),
            "chunk_bytes": self.chunk_bytes,
            "n_chunks": self.n_chunks,
            "crcs": list(self.crcs),
            **self.extra,
        }

    def chunk(self, k: int) -> bytes:
        if not (0 <= k < self.n_chunks):
            raise IndexError(k)
        off = k * self.chunk_bytes
        return self.payload[off:off + self.chunk_bytes]


class SourceCache:
    """Donor-side pin registry: newest ``keep`` pins, idle-TTL-bounded
    (``ttl_s`` of no chunk/meta activity drops a pin; active serving
    keeps it alive however long the transfer takes).

    Every meta request pins a FRESH serialization (the live store
    moves continuously; a stale pin would install an old frontier and
    immediately re-trigger need_snap).  Keeping the previous pin
    alive lets a pull already in flight finish against its own chain
    while a second peer starts on a newer one."""

    def __init__(self, keep: int = 2, ttl_s: float = 300.0):
        self.keep = keep
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._pins: dict[str, SnapshotSource] = {}

    def pin(self, src: SnapshotSource) -> SnapshotSource:
        with self._lock:
            self._pins[src.id] = src
            now = time.monotonic()
            live = sorted(self._pins.values(),
                          key=lambda s: s.pinned_at, reverse=True)
            keep = [s for s in live[:self.keep]
                    if now - s.pinned_at <= self.ttl_s]
            self._pins = {s.id: s for s in keep}
        return src

    def get(self, source_id: str) -> SnapshotSource | None:
        with self._lock:
            src = self._pins.get(source_id)
            if src is None:
                return None
            now = time.monotonic()
            if now - src.pinned_at > self.ttl_s:
                self._pins.pop(source_id, None)
                return None
            # idle-TTL: serving refreshes the pin (and keeps it ahead
            # in pin()'s newest-first ranking), so a transfer slower
            # than ttl_s x bandwidth can't expire MID-STREAM and
            # strand the receiver in refetch-from-chunk-0 churn —
            # only ttl_s of inactivity drops a pin
            src.pinned_at = now
            return src


class ChunkVerifier:
    """Rolling-chain verification of received chunks, routed like the
    PR 3 replay lane: seedable host digest without an accelerator,
    GF(2) seed-stitched device batch with one (``route`` forces)."""

    def __init__(self, route: str | None = None):
        if route is None:
            from ..wal.replay_device import _accelerator_absent

            route = "host" if _accelerator_absent() else "device"
        if route not in ("host", "device"):
            raise ValueError(f"unknown verify route {route!r}")
        self.route = route

    def verify(self, chunks: list[bytes], prevs: list[int],
               stored: list[int]) -> list[bool]:
        """Per-chunk verdicts for ``update(prevs[i], chunks[i]) ==
        stored[i]``.  Chunks are independent given their
        predecessors' STORED values (the chain induction), so the
        device form verifies a whole contiguous run in one batch."""
        if not chunks:
            return []
        if self.route == "host":
            return [crc_update(p, c) == s
                    for c, p, s in zip(chunks, prevs, stored)]
        from ..ops.crc_device import (
            chain_links_injected,
            inject_seeds,
            raw_crc_batch,
        )

        lens = np.asarray([len(c) for c in chunks], np.int64)
        width = int(lens.max()) + 4
        rows = np.zeros((len(chunks), width), np.uint8)
        for i, c in enumerate(chunks):
            rows[i, width - len(c):] = np.frombuffer(c, np.uint8)
        inject_seeds(rows, lens, np.asarray(prevs, np.uint32))
        ok = np.asarray(chain_links_injected(
            raw_crc_batch(rows), np.asarray(stored, np.uint32)))
        return [bool(x) for x in ok]


class ChunkPuller:
    """Windowed chunk pull of one pinned snapshot over a peerlink
    pipe channel (receiver side).

    ``run()`` returns the assembled, fully verified payload bytes or
    raises :class:`SnapStreamError` / :class:`StaleSourceError`.  Up
    to ``window`` chunk requests ride the channel ahead of their
    responses; verification consumes chunks in order (the chain), so
    a verify of chunk k overlaps the fetch of chunks k+1..k+w.  A
    CRC-rejected chunk is refetched (bounded by ``max_rejects``); a
    transport failure re-requests the lost chunks over the channel's
    automatic reconnect — resume from the last verified chunk, never
    from scratch."""

    def __init__(self, url: str, meta: dict, *, ssl_context=None,
                 timeout: float = 1.0, window: int = 4,
                 verifier: ChunkVerifier | None = None,
                 max_rejects: int = 8, deadline_s: float = 300.0,
                 stall_s: float = 20.0, abort=None,
                 on_reject=None, name: str = "snapstream"):
        from ..server.peerlink import PipeChannel

        self.meta = meta
        self._abort = abort or (lambda: False)
        # fired per rejected chunk index: the receiving server's
        # flight recorder rides this so chunk_reject outcomes reach
        # its black box too (the metric alone is process-wide)
        self._on_reject = on_reject or (lambda k: None)
        self.n = int(meta["n_chunks"])
        self.size = int(meta["size"])
        self.chunk_bytes = int(meta["chunk_bytes"])
        self.crcs = [int(c) for c in meta["crcs"]]
        if len(self.crcs) != self.n:
            raise SnapStreamError("meta crcs/n_chunks mismatch")
        self.source_id = str(meta["id"])
        self.window = max(1, window)
        self.max_rejects = max_rejects
        self.deadline_s = deadline_s
        self.stall_s = min(stall_s, deadline_s)
        self.verifier = verifier or ChunkVerifier()
        self._events: queue.Queue = queue.Queue()
        self._chan = PipeChannel(
            url, CHUNK_PATH, stripes=1, timeout=timeout,
            read_timeout=max(4.0 * timeout, 10.0),
            ssl_context=ssl_context,
            on_resp=lambda seq, status, body:
                self._events.put(("resp", seq, status, body)),
            on_fail=lambda seqs, reason:
                self._events.put(("fail", seqs, reason)),
            name=name)

    def close(self) -> None:
        self._chan.close()

    def _request(self, k: int) -> None:
        self._chan.send(k, f"{self.source_id} {k}".encode())

    def run(self) -> bytes:
        if self.n == 0:
            return b""
        deadline = time.monotonic() + self.deadline_s
        buffered: dict[int, bytes] = {}
        outstanding: set[int] = set()
        t_req: dict[int, float] = {}
        rejects = 0
        fail_streak = 0       # consecutive transport-failure events
        last_progress = time.monotonic()
        next_send = 0
        next_verify = 0
        out = bytearray()

        def send_window():
            nonlocal next_send
            while (len(outstanding) < self.window
                   and next_send < self.n):
                k = next_send
                next_send += 1
                if k < next_verify or k in buffered:
                    continue  # verified/arrived already (resume path)
                outstanding.add(k)
                t_req.setdefault(k, time.monotonic())
                self._request(k)

        def refetch(k: int) -> None:
            if k < next_verify or k in buffered:
                return
            outstanding.add(k)
            t_req[k] = time.monotonic()
            self._request(k)

        send_window()
        while next_verify < self.n:
            if self._abort():
                raise SnapStreamError("aborted (server stopping)")
            left = deadline - time.monotonic()
            if left <= 0:
                raise SnapStreamError(
                    f"snapshot stream deadline exceeded at chunk "
                    f"{next_verify}/{self.n}")
            try:
                ev = self._events.get(timeout=min(left, 1.0))
            except queue.Empty:
                continue
            kind = ev[0]
            if kind == "fail":
                _, seqs, reason = ev
                live = [k for k in seqs
                        if k in outstanding and k not in buffered]
                if live:
                    # the stream aborts on STALL, not on a failure
                    # count: a donor outage shorter than stall_s is
                    # ridden out and resumed from the verified
                    # frontier (only the lost chunks re-request,
                    # never the prefix).  The paced retry keeps a
                    # fast-failing donor from being hammered.
                    fail_streak += 1
                    if (time.monotonic() - last_progress
                            > self.stall_s):
                        raise SnapStreamError(
                            f"no verified chunk for {self.stall_s:g}s"
                            f" ({reason}); aborting at "
                            f"{next_verify}/{self.n}")
                    if self._abort():
                        raise SnapStreamError(
                            "aborted (server stopping)")
                    time.sleep(min(0.02 * fail_streak, 0.3))
                    for k in live:
                        outstanding.discard(k)
                    for k in live:
                        refetch(k)
                continue
            _, k, status, body = ev
            # receiver-side failpoint (PR 10): drop loses this
            # response (paced refetch recovers, same as a transport
            # hiccup); corrupt flips a byte INTO the CRC verifier —
            # the reject+refetch path, without donor cooperation
            try:
                act = _faults.hit("snapstream.pull")
            except OSError as e:
                raise SnapStreamError(
                    f"injected pull fault: {e}") from e
            if act == _faults.DROP:
                outstanding.discard(k)
                fail_streak += 1
                time.sleep(min(0.02 * fail_streak, 0.3))
                refetch(k)
                continue
            if act == _faults.CORRUPT:
                body = _faults.flip_byte(body)
            if status in (404, 410):
                raise StaleSourceError(
                    f"donor no longer pins source {self.source_id}")
            if status != 200:
                outstanding.discard(k)
                fail_streak += 1
                if time.monotonic() - last_progress > self.stall_s:
                    raise SnapStreamError(
                        f"donor answering {status} persistently")
                time.sleep(min(0.02 * fail_streak, 0.3))
                refetch(k)
                continue
            if k not in outstanding or k < next_verify:
                continue  # duplicate / already-verified chunk
            outstanding.discard(k)
            buffered[k] = body
            # verify the contiguous run now available — one batch
            # through the routed verifier (device: one matmul)
            run_ks = []
            while (next_verify + len(run_ks)) in buffered:
                run_ks.append(next_verify + len(run_ks))
            if not run_ks:
                send_window()
                continue
            datas = [buffered[j] for j in run_ks]
            prevs = [self.crcs[j - 1] if j else 0 for j in run_ks]
            stored = [self.crcs[j] for j in run_ks]
            now = time.monotonic()
            oks = self.verifier.verify(datas, prevs, stored)
            for j, okd in zip(run_ks, oks):
                if not okd:
                    # corrupt chunk: reject + refetch, NEVER install
                    _install_ctr("chunk_reject").inc()
                    self._on_reject(j)
                    rejects += 1
                    log.warning(
                        "snapstream: chunk %d/%d failed rolling-CRC "
                        "verify; refetching (reject %d/%d)", j,
                        self.n, rejects, self.max_rejects)
                    if rejects > self.max_rejects:
                        raise SnapStreamError(
                            f"chunk {j} rejected past the "
                            f"corruption budget")
                    del buffered[j]
                    refetch(j)
                    break
                expect = (self.chunk_bytes
                          if j < self.n - 1 else
                          self.size - (self.n - 1) * self.chunk_bytes)
                if len(buffered[j]) != expect:
                    raise SnapStreamError(
                        f"chunk {j} size {len(buffered[j])} != "
                        f"{expect}")
                out += buffered.pop(j)
                next_verify = j + 1
                fail_streak = 0
                last_progress = now
                t0 = t_req.pop(j, None)
                if t0 is not None:
                    _CHUNK_HIST.observe(now - t0)
            send_window()
        if len(out) != self.size:
            raise SnapStreamError(
                f"assembled {len(out)} bytes != meta size {self.size}")
        return bytes(out)


__all__ = [
    "CHUNK_PATH",
    "ChunkPuller",
    "ChunkVerifier",
    "DEFAULT_CHUNK_BYTES",
    "FRONTIER_PATH",
    "META_PATH",
    "SnapStreamError",
    "SnapshotSource",
    "SourceCache",
    "StaleSourceError",
    "chunk_crcs",
]
