"""Snapshot persistence (reference snap/snapshotter.go:29-150).

Files are named ``%016x-%016x.snap`` (term, index) and contain a
snappb wrapper {crc, data} where crc is the whole-blob CRC32C and data
the marshaled raftpb Snapshot.  Load walks newest-first, quarantining
unreadable files as ``.broken`` so one corruption never masks an older
good snapshot.
"""

from __future__ import annotations

import errno
import logging
import os
from typing import Callable

from ..crc import value as crc_value
from ..utils import faults as _faults
from ..utils.errors import EtcdNoSpace
from ..utils.fsio import fsync as fsio_fsync, fsync_dir
from ..wire import SnapPb, Snapshot, is_empty_snap
from ..wire.proto import ProtoError

log = logging.getLogger(__name__)

SNAP_SUFFIX = ".snap"

#: snapshots retained after a successful save (newest-first); older
#: files and quarantined ``.broken`` files beyond the window are
#: purged so the snap dir stays bounded under sustained traffic
#: (PR 6).  One durable snapshot would suffice for recovery; keeping
#: a few preserves the load() fallback ladder against a corrupt
#: newest file.
DEFAULT_SNAP_KEEP = 5


class SnapError(Exception):
    pass


class NoSnapshotError(SnapError):
    """No available snapshot (ErrNoSnapshot)."""


class SnapCRCMismatchError(SnapError):
    """Whole-file CRC mismatch (ErrCRCMismatch)."""


class SnapEmptyError(SnapError):
    """Empty snapshot file or payload."""


def snap_name(term: int, index: int) -> str:
    return f"{term:016x}-{index:016x}{SNAP_SUFFIX}"


class Snapshotter:
    """``crc_fn`` computes CRC32C of a blob from a zero seed; the
    default is the host path and the device kernel
    (ops.crc_kernel.device_crc32c) drops in for large blobs."""

    def __init__(self, dirpath: str,
                 crc_fn: Callable[[bytes], int] | None = None,
                 keep: int = DEFAULT_SNAP_KEEP):
        self.dir = dirpath
        self.crc_fn = crc_fn or crc_value
        if keep < 1:
            raise ValueError(f"keep={keep} must be >= 1 (a purge "
                             f"that deletes every snapshot would "
                             f"strand the GC'd WAL chain)")
        self.keep = keep

    def save_snap(self, snapshot: Snapshot) -> None:
        """No-op for empty snapshots (snapshotter.go:39-44)."""
        if is_empty_snap(snapshot):
            return
        self._save(snapshot)

    def _save(self, snapshot: Snapshot) -> None:
        fname = snap_name(snapshot.term, snapshot.index)
        b = snapshot.marshal()
        crc = self.crc_fn(b)
        d = SnapPb(crc=crc, data=b).marshal()
        fpath = os.path.join(self.dir, fname)
        # contents + directory entry fsynced before returning: the
        # callers cut the WAL right after save_snap, so a snapshot
        # that evaporates in a crash would strand the log tail
        # behind a segment boundary with no state to stand on.
        # ENOSPC (real or the snap.save failpoint) removes the
        # partial file — older durable snapshots remain, the caller
        # enters NOSPACE mode — and any OTHER fsync failure is
        # fail-stop (utils/fsio.fsync semantics, shared rule).
        try:
            _faults.hit("snap.save")
            with open(fpath, "wb") as f:
                f.write(d)
                # fsio.fsync: ENOSPC -> EtcdNoSpace, anything else
                # fail-stop (never returns on failure)
                fsio_fsync(f)
        except EtcdNoSpace:
            # fsync-time full disk: drop the partial file so a
            # truncated snapshot can never be loaded, then degrade
            try:
                os.remove(fpath)
            except OSError:
                pass
            fsync_dir(self.dir)
            raise
        except OSError as e:
            # open/write-time failure (fsync errors never get here)
            if e.errno == errno.ENOSPC:
                try:
                    os.remove(fpath)
                except OSError:
                    pass
                fsync_dir(self.dir)
                raise EtcdNoSpace(
                    cause=f"snapshot save {fname}: {e}") from e
            _faults.fail_stop(
                f"snapshot write failed on {fpath}: {e}", e)
        fsync_dir(self.dir)
        # the NEW snapshot is durable (file + dir entry) — only now
        # may older snapshots be deleted (delete-after-fsync; the
        # durability-ordering checker's unsynced-delete rule)
        self.purge()

    def purge(self) -> None:
        """Delete snapshots beyond the newest ``keep`` plus every
        quarantined ``.broken`` file older than the newest snapshot.

        Without this ``_snap_names`` grows forever under sustained
        snapshotting.  Crash-safe at any point: snapshots are
        independent files, so any surviving subset keeps load()
        working as long as the newest (already fsynced by _save) is
        present; a ``.broken`` newer than the newest kept snapshot is
        retained so the quarantine evidence of a corrupt latest file
        is not destroyed before an operator can see it."""
        try:
            names = os.listdir(self.dir)
        except OSError:  # pragma: no cover - dir vanished
            return
        snaps = sorted((n for n in names if n.endswith(SNAP_SUFFIX)),
                       reverse=True)
        doomed = snaps[self.keep:]
        if snaps:
            newest_kept = snaps[0]
            doomed += [n for n in names
                       if n.endswith(".broken")
                       and n[:-len(".broken")] < newest_kept]
        if not doomed:
            return
        for n in doomed:
            try:
                os.remove(os.path.join(self.dir, n))
            except OSError as e:  # pragma: no cover - racing purge
                log.warning("snapshotter purge cannot remove %s: %s",
                            n, e)
        # unlinks must stick: a crash-reverted purge would regrow the
        # dir and (worse) resurrect a .broken-masked ordering
        fsync_dir(self.dir)
        log.info("snapshotter: purged %d old snapshot file(s), "
                 "%d kept", len(doomed), min(len(snaps), self.keep))

    def retained_floor(self) -> int | None:
        """Smallest raft index among the retained ``.snap`` files —
        THE safe WAL-GC boundary.  Segments covering indexes at or
        above this must survive: ``load()`` falls back across every
        kept snapshot when the newest is corrupt, and the fallback
        target needs WAL coverage from ITS index to replay forward.
        GC'ing at the newest snapshot's index instead would make a
        single corrupt newest file unrecoverable despite K-1 good
        older snapshots (review finding, PR 6)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return None
        idxs = []
        for n in names:
            if not n.endswith(SNAP_SUFFIX):
                continue
            try:
                _, _, idx_s = n[:-len(SNAP_SUFFIX)].partition("-")
                idxs.append(int(idx_s, 16))
            except ValueError:
                continue
        return min(idxs) if idxs else None

    def load(self) -> Snapshot:
        """Newest-first, falling back across corrupt files
        (snapshotter.go:62-74)."""
        names = self._snap_names()
        err: Exception = NoSnapshotError(self.dir)
        for name in names:
            try:
                return self._load_snap(name)
            except SnapError as e:
                err = e
        raise err

    def _load_snap(self, name: str) -> Snapshot:
        """Any failure quarantines the file (snapshotter.go:81-85
        defers renameBroken on every error path, reads included)."""
        fpath = os.path.join(self.dir, name)
        try:
            with open(fpath, "rb") as f:
                b = f.read()
        except OSError as e:
            log.warning("snapshotter cannot read file %s: %s", name, e)
            self._rename_broken(fpath)
            raise SnapError(str(e)) from e
        try:
            if not b:
                raise SnapEmptyError(name)
            serialized = SnapPb.unmarshal(b)
            if serialized.data is None:
                raise SnapEmptyError(name)
            crc = self.crc_fn(serialized.data)
            if crc != serialized.crc:
                log.warning("corrupted snapshot file %s: crc mismatch", name)
                raise SnapCRCMismatchError(name)
            try:
                return Snapshot.unmarshal(serialized.data)
            except ProtoError as e:
                raise SnapError(f"corrupted snapshot {name}: {e}") from e
        except ProtoError as e:
            log.warning("corrupted snapshot file %s: %s", name, e)
            self._rename_broken(fpath)
            raise SnapError(str(e)) from e
        except SnapError:
            self._rename_broken(fpath)
            raise

    def _snap_names(self) -> list[str]:
        """Snapshot filenames newest-first (snapshotter.go:115-131)."""
        names = os.listdir(self.dir)
        snaps = [n for n in names if n.endswith(SNAP_SUFFIX)]
        for n in names:
            if not n.endswith(SNAP_SUFFIX):
                log.warning("unexpected non-snap file %s", n)
        if not snaps:
            raise NoSnapshotError(self.dir)
        return sorted(snaps, reverse=True)

    @staticmethod
    def _rename_broken(path: str) -> None:
        broken = path + ".broken"
        try:
            os.rename(path, broken)
            # quarantine must stick across a crash — an un-fsynced
            # rename can revert, and the corrupt file would then
            # mask older good snapshots again on the next load
            fsync_dir(os.path.dirname(path))
        except OSError as e:  # pragma: no cover
            log.warning("cannot rename broken snapshot %s: %s", path, e)
