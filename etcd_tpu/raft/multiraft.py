"""Co-hosted multi-raft runtime: G groups × M members, batched.

The reference hosts ONE raft group per process and tests multi-node
behavior with an in-process fake network pump (raft_test.go:1203-1263).
This runtime is the batched generalization: member ``m`` of *every*
group lives in one ``GroupState`` batch (arrays [G]), so a full
M-member cluster of G co-hosted groups is M pytrees, and "message
delivery" between co-hosted members is array exchange — no
serialization, no sockets (SURVEY §5.8: intra-slice communication is
sharded-array collectives; inter-member DCN transport stays at the
server layer for cross-host peers).

The hot path (propose → replicate → respond → commit) runs entirely
as batched device ops (raft/batched.py); elections run batched too
(grant_vote quorum across members), fired by the batched tick timers.

Payload bytes stay host-side (a per-group ring keyed by log index —
the wrong shape for HBM), mirroring the split in SURVEY §7: the
device owns index/term/commit math, the host owns opaque blobs.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .batched import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    GroupState,
    grant_vote,
    init_groups,
    leader_append,
    compact as compact_batch,
    maybe_append,
    maybe_commit,
    progress_update,
    restore_snapshot,
    term_at,
    tick as tick_batch,
)


class MultiRaft:
    """G co-hosted groups, M members each, batched across groups."""

    def __init__(self, g: int, m: int, cap: int, election: int = 10,
                 max_batch_ents: int = 8, seed: int = 0):
        self.g, self.m, self.cap = g, m, cap
        self.e = max_batch_ents
        rng = np.random.default_rng(seed)
        self.states: list[GroupState] = []
        for slot in range(m):
            st = init_groups(g, m, cap, election=election)
            # randomized election timeouts (raft.go:611-617): each
            # member draws [election, 2*election) per group
            st = st._replace(timeout=jnp.asarray(
                rng.integers(election, 2 * election, size=g), jnp.int32))
            self.states.append(st)
        self.leader = np.full(g, -1, np.int32)  # member slot per group
        # host-side payload store: per-group dict index -> bytes
        self.payloads: list[dict[int, bytes]] = [dict() for _ in range(g)]

    # -- elections (batched across groups) ------------------------------

    def campaign(self, slot: int, mask: np.ndarray | None = None
                 ) -> np.ndarray:
        """Member ``slot`` campaigns for the masked groups
        (raft.go:358-370 batched): term+1, vote self, request votes
        from every other member, count the quorum.

        Returns the [G] bool mask of groups where it won.
        """
        g, m = self.g, self.m
        mask = np.ones(g, bool) if mask is None else mask
        mj = jnp.asarray(mask)
        cand = self.states[slot]
        new_term = cand.term + mj.astype(jnp.int32)
        cand = cand._replace(
            term=new_term,
            role=jnp.where(mj, CANDIDATE, cand.role),
            vote=jnp.where(mj, slot, cand.vote))

        votes = np.ones(g, np.int64)  # own vote
        cand_last = cand.last
        cand_lterm = term_at(cand.log_term, cand.offset, cand.last,
                             cand.last)
        for peer in range(m):
            if peer == slot:
                continue
            st = self.states[peer]
            # msgVote carries the candidate term; peers at a lower
            # term adopt it (raft.go:388-396 batched)
            adopt = mj & (cand.term > st.term)
            st = st._replace(
                term=jnp.where(adopt, cand.term, st.term),
                vote=jnp.where(adopt, -1, st.vote),
                role=jnp.where(adopt, FOLLOWER, st.role))
            st, granted = grant_vote(
                st, cand_last, cand_lterm, cand.term,
                jnp.full((g,), slot, jnp.int32), active=mj)
            # granting a vote resets the election timer (the reference
            # resets on any message from a legitimate candidate)
            st = st._replace(elapsed=jnp.where(granted, 0, st.elapsed))
            self.states[peer] = st
            votes += np.asarray(granted).astype(np.int64)

        won = mask & (votes >= (m // 2 + 1))
        wj = jnp.asarray(won)
        # winners become leader; note the reference appends an empty
        # entry on becoming leader (raft.go:329-348) so the new term
        # has a committable entry — replicated via the normal path
        cand = cand._replace(
            role=jnp.where(wj, LEADER, cand.role),
            lead=jnp.where(wj, slot, cand.lead),
            match=jnp.where(wj[:, None], 0, cand.match),
            next_=jnp.where(wj[:, None], cand.last[:, None] + 1,
                            cand.next_))
        self.states[slot] = cand
        won_np = np.asarray(wj)
        self.leader = np.where(won_np, slot, self.leader).astype(np.int32)
        if won_np.any():
            # Entries beyond the winner's last were never committed
            # (Raft safety: committed entries survive elections), so a
            # deposed leader's payloads at those indices are garbage
            # the new term may overwrite — drop them.
            winner_last = np.asarray(cand.last)
            for gi in np.nonzero(won_np)[0]:
                p = self.payloads[gi]
                cut = int(winner_last[gi])
                if p and max(p) > cut:  # skip the common no-op case
                    self.payloads[gi] = {
                        k: v for k, v in p.items() if k <= cut}
            # the becoming-leader empty entry (raft.go:329-348)
            self.propose(np.where(won_np, 1, 0).astype(np.int32))
        return won_np

    # -- the replication hot path ---------------------------------------

    def propose(self, n_new: np.ndarray,
                data: list[list[bytes]] | None = None,
                drop=None) -> np.ndarray:
        """Append ``n_new[g]`` proposals to each group's leader and
        run one full replicate→respond→commit round.  Returns the
        per-group count of newly committed entries."""
        g, m = self.g, self.m
        lead = self.leader
        n_new = np.asarray(n_new, np.int32)

        # capture append bases from members that really ARE leader
        # (a deposed member may still be in self.leader briefly)
        valid = np.zeros(g, bool)
        base = np.zeros(g, np.int64)
        for slot in range(m):
            sel = lead == slot
            if not sel.any():
                continue
            st = self.states[slot]
            is_lead = sel & (np.asarray(st.role) == LEADER)
            valid |= is_lead
            base[is_lead] = np.asarray(st.last)[is_lead]

        for slot in range(m):
            sel = jnp.asarray(lead == slot)
            if not bool(np.asarray(sel).any()):
                continue
            st = self.states[slot]
            st, err = leader_append(
                st, jnp.where(sel, jnp.asarray(n_new), 0),
                jnp.full((g,), slot, jnp.int32), active=sel)
            if bool(np.asarray(err).any()):
                raise OverflowError("log capacity exceeded; compact")
            self.states[slot] = st

        # payloads recorded only after the appends landed, keyed from
        # the validated leader's pre-append last index
        if data is not None:
            for gi in np.nonzero(valid)[0]:
                for j, blob in enumerate(data[gi][:int(n_new[gi])]):
                    self.payloads[gi][int(base[gi]) + 1 + j] = blob
        return self.replicate(drop=drop)

    def replicate(self, drop=None) -> np.ndarray:
        """One replication round for every group: leaders send their
        pending window to every follower member, absorb the responses,
        advance the quorum commit (the batched §3.2 inner loop).

        ``drop``: optional fault-injection mask — ``drop[(a, b)]`` is a
        [G] bool array dropping messages from member a to member b for
        the masked groups, the batched analog of the reference's
        per-edge lossy fake network (raft_test.go:1258-1287).  Dropped
        appends are simply retried on a later round: the protocol's
        fire-and-forget contract (server.go:202-206)."""
        g, m, e = self.g, self.m, self.e
        drop = drop or {}
        commits_before = self._commit_vector()

        for slot in range(m):
            sel_np = self.leader == slot
            if not sel_np.any():
                continue
            sel = jnp.asarray(sel_np)
            lst = self.states[slot]
            for peer in range(m):
                if peer == slot:
                    continue
                pst = self.states[peer]
                # window: follower's next.. min(next+E-1, leader last)
                nxt = jnp.take_along_axis(
                    lst.next_, jnp.full((g, 1), peer, jnp.int32),
                    axis=1)[:, 0]
                # followers at a lower term adopt the leader's
                # (raft.go:388-396); stale leaders don't send
                send = sel & (lst.term >= pst.term) & \
                    (lst.role == LEADER)
                if (slot, peer) in drop:
                    send = send & ~jnp.asarray(drop[(slot, peer)])
                adopt = send & (lst.term > pst.term)
                pst = pst._replace(
                    term=jnp.where(adopt, lst.term, pst.term),
                    vote=jnp.where(adopt, -1, pst.vote),
                    role=jnp.where(send, FOLLOWER, pst.role),
                    lead=jnp.where(send, slot, pst.lead))
                # slow follower fell behind the leader's compaction
                # point: send a snapshot instead (raft.go:207-209,
                # needSnapshot :556); the follower's log collapses to
                # the leader's offset entry and normal appends resume
                needs_snap = send & (nxt <= lst.offset) & \
                    (lst.offset > 0)
                if bool(np.asarray(needs_snap).any()):
                    snap_term = term_at(lst.log_term, lst.offset,
                                        lst.last, lst.offset)
                    follower_commit = pst.commit
                    pst, installed = restore_snapshot(
                        pst, lst.offset, snap_term,
                        commit=jnp.minimum(lst.commit, lst.offset),
                        active=needs_snap)
                    # installed lanes ack the snapshot index; lanes
                    # that rejected (commit already past it) reply
                    # with their commit, repairing the leader's stale
                    # next_ without any truncation (raft.go:419-424)
                    peer_v = jnp.full((g,), peer, jnp.int32)
                    lst = progress_update(
                        lst, peer_v, lst.offset, active=installed)
                    rejected = needs_snap & ~installed
                    lst = progress_update(
                        lst, peer_v, follower_commit, active=rejected)
                    nxt = jnp.where(
                        installed, lst.offset + 1,
                        jnp.where(rejected, follower_commit + 1, nxt))

                prev_idx = nxt - 1
                prev_term = term_at(lst.log_term, lst.offset, lst.last,
                                    prev_idx)
                n_send = jnp.clip(lst.last - prev_idx, 0, e)
                ent_idx = prev_idx[:, None] + 1 + \
                    jnp.arange(e, dtype=jnp.int32)
                ent_terms = term_at(lst.log_term, lst.offset, lst.last,
                                    ent_idx)
                pst, ok, err = maybe_append(
                    pst, prev_idx, prev_term, ent_terms, n_send,
                    lst.commit, active=send)
                if bool(np.asarray(err).any()):
                    raise RuntimeError("append conflict below commit")
                # any append from the legitimate leader resets the
                # follower's election timer (otherwise every follower
                # would depose a healthy leader each `timeout` ticks)
                pst = pst._replace(
                    elapsed=jnp.where(send, 0, pst.elapsed))
                self.states[peer] = pst
                # msgAppResp: success → progress update; reject →
                # decrement next (raft.go:464-470 batched); the
                # response direction can be dropped independently
                resp_ok = send
                if (peer, slot) in drop:
                    resp_ok = resp_ok & ~jnp.asarray(drop[(peer, slot)])
                acked = prev_idx + n_send
                lst = progress_update(lst, jnp.full((g,), peer,
                                                    jnp.int32),
                                      acked, active=resp_ok & ok)
                reject = resp_ok & ~ok
                if bool(np.asarray(reject).any()):
                    onehot = jnp.arange(m) == peer
                    dec = jnp.maximum(nxt - 1, 1)
                    lst = lst._replace(next_=jnp.where(
                        reject[:, None] & onehot[None, :],
                        dec[:, None], lst.next_))
            lst = maybe_commit(lst)
            self.states[slot] = lst
        return self._commit_vector() - commits_before

    def mark_applied(self, upto: np.ndarray) -> None:
        """The host consumer declares it has applied entries up to
        ``upto[g]`` (clamped to each member's commit).  Compaction
        never slides past this point, so committed-but-unconsumed
        payloads stay retrievable."""
        upto = jnp.asarray(upto, jnp.int32)
        for slot in range(self.m):
            st = self.states[slot]
            st = st._replace(applied=jnp.maximum(
                st.applied, jnp.minimum(upto, st.commit)))
            self.states[slot] = st

    def compact(self, upto: np.ndarray | None = None) -> None:
        """Compact every member's log at its applied index (the
        reference couples this to the snapshot trigger,
        server.go:313-316 + log.go:161); payloads below the
        compaction point are dropped from the host ring.  Call
        :meth:`mark_applied` first — compaction never outruns what
        the consumer declared applied."""
        for slot in range(self.m):
            st = self.states[slot]
            idx = st.applied
            if upto is not None:
                idx = jnp.minimum(idx, jnp.asarray(upto, jnp.int32))
            st, err = compact_batch(st, jnp.maximum(idx, st.offset))
            if bool(np.asarray(err).any()):
                raise RuntimeError("compact out of bounds")
            self.states[slot] = st
        cut = np.min(np.stack(
            [np.asarray(st.offset) for st in self.states]), axis=0)
        for gi in range(self.g):
            p = self.payloads[gi]
            c = int(cut[gi])
            if p and min(p) < c:
                self.payloads[gi] = {k: v for k, v in p.items()
                                     if k >= c}

    def tick(self) -> None:
        """Advance every member's timers; campaign where they fire."""
        for slot in range(self.m):
            st, elect, _beat = tick_batch(self.states[slot])
            self.states[slot] = st
            fire = np.asarray(elect)
            if fire.any():
                self.campaign(slot, fire)

    # -- views -----------------------------------------------------------

    def _commit_vector(self) -> np.ndarray:
        """Max commit across members per group (any member's commit
        is authoritative once set)."""
        return np.max(np.stack(
            [np.asarray(st.commit) for st in self.states]), axis=0)

    def commit_index(self) -> np.ndarray:
        return self._commit_vector()

    def committed_payload(self, group: int, index: int) -> bytes | None:
        return self.payloads[group].get(index)

    def log_terms(self, slot: int) -> np.ndarray:
        return np.asarray(self.states[slot].log_term)
